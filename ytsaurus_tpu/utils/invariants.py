"""Invariant checking: the debug-build sanitizer analog.

Ref: the reference leans on debug-build assertions (YT_VERIFY /
VERIFY_*), TSAN/ASAN builds, and stress suites to catch state
corruption early.  A Python framework has no TSAN, so this module
provides the piece that carries over: STRUCTURAL INVARIANT checks at
subsystem boundaries, enabled via YT_TPU_INVARIANTS=1 (tests/conftest
turns them on for the whole suite, so every integration scenario runs
"sanitized"; production leaves them off — some checks walk whole
stores).

Registered checks (grown alongside the subsystems):
  tablet   — per store: versioned rows key-ordered, no duplicate
             (key, timestamp) version
  wal      — epoch tags non-decreasing along the committed log (the
             invariant VR-style recovery depends on)
  chunks   — column planes share one capacity; row_count <= capacity

Usage: `check("tablet", tablet_obj)` at a boundary — a no-op unless
enabled; violations raise InvariantError with enough context to debug
the corruption at its SOURCE rather than at a distant read.
"""

from __future__ import annotations

import os

from ytsaurus_tpu.errors import YtError


class InvariantError(YtError):
    pass


def enabled() -> bool:
    return os.environ.get("YT_TPU_INVARIANTS", "") not in ("", "0")


def _fail(domain: str, message: str) -> None:
    raise InvariantError(f"INVARIANT[{domain}]: {message}")


def check_chunk(chunk) -> None:
    cap = chunk.capacity
    if chunk.row_count > cap:
        _fail("chunks", f"row_count {chunk.row_count} > capacity {cap}")
    for name, col in chunk.columns.items():
        if col.data.shape[0] != cap or col.valid.shape[0] != cap:
            _fail("chunks",
                  f"column {name!r} planes {col.data.shape[0]}/"
                  f"{col.valid.shape[0]} != capacity {cap}")


def check_wal(records) -> None:
    """Epoch tags must be non-decreasing along a committed log — the
    property recovery's (last-epoch, length) rule rests on."""
    from ytsaurus_tpu.cypress.quorum import record_epoch
    last = 0
    for i, record in enumerate(records):
        epoch = record_epoch(record)
        if epoch < last:
            _fail("wal", f"epoch regressed at record {i}: "
                         f"{epoch} after {last}")
        last = max(last, epoch)


def check_tablet(tablet) -> None:
    """Per-STORE structural checks (no whole-tablet materialization —
    flush/compact hooks must stay O(store), not O(table)):
    - versioned rows ordered by key (versions of one key adjacent),
    - no duplicate (key, timestamp) version within a store."""
    stores = [getattr(tablet, "active_store", None)] + \
        list(getattr(tablet, "passive_stores", ()) or ())
    key_names = tablet.schema.key_column_names
    for store in stores:
        if store is None or not hasattr(store, "versioned_rows"):
            continue
        prev_key = None
        seen_ts: set = set()
        for vrow in store.versioned_rows():
            key = tuple(_orderable(vrow[k]) for k in key_names)
            if prev_key is not None and key < prev_key:
                _fail("tablet", f"store keys out of order: {key} after "
                                f"{prev_key}")
            if key != prev_key:
                seen_ts = set()
            ts = vrow["$timestamp"]
            if ts in seen_ts:
                _fail("tablet", f"duplicate version timestamp {ts} for "
                                f"key {key}")
            seen_ts.add(ts)
            prev_key = key


def _orderable(value):
    """Null-safe, cross-type-safe ordering key (null sorts first)."""
    if value is None:
        return (0, 0)
    if isinstance(value, bool):
        return (1, int(value))
    if isinstance(value, (int, float)):
        return (2, value)
    if isinstance(value, bytes):
        return (3, value)
    if isinstance(value, str):
        return (3, value.encode())
    return (4, repr(value))


def check_versioned_rows(subject) -> None:
    """(key_names, rows) about to be PERSISTED by a flush/compaction:
    key-ordered, and no (key, timestamp) version recorded twice — the
    strongest place to check, because it sees the exact bytes headed
    for the chunk regardless of which store they came from."""
    key_names, rows = subject
    prev_key = None
    seen_ts: set = set()
    for i, row in enumerate(rows):
        key = tuple(_orderable(row[k]) for k in key_names)
        if prev_key is not None and key < prev_key:
            _fail("versioned_rows",
                  f"row {i}: key {key} out of order after {prev_key}")
        if key != prev_key:
            seen_ts = set()
        ts = row["$timestamp"]
        if ts in seen_ts:
            _fail("versioned_rows",
                  f"row {i}: duplicate version timestamp {ts} for key "
                  f"{key}")
        seen_ts.add(ts)
        prev_key = key


_CHECKS = {
    "chunks": check_chunk,
    "wal": check_wal,
    "tablet": check_tablet,
    "versioned_rows": check_versioned_rows,
}


def check(domain: str, subject) -> None:
    """Boundary hook: no-op unless YT_TPU_INVARIANTS is set."""
    if not enabled():
        return
    checker = _CHECKS.get(domain)
    if checker is None:
        _fail(domain, "unknown invariant domain")
    checker(subject)
