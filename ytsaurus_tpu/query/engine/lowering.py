"""Plan lowering: typed IR → a staged XLA pipeline over columnar planes.

The reference JIT-compiles a per-row push pipeline (scan→filter→group→order→
project, cg_fragment_compiler.cpp).  Here each clause becomes a batch
transformation over static-capacity planes:

  filter   = predicate mask (no data movement)
  group    = lexsort by key planes → segment boundaries → segment reductions
  order    = lexsort by order keys → gather
  project  = elementwise expression evaluation
  limit    = compaction (stable sort by ~mask) + static slice

`prepare()` runs per chunk on the host (binding vocabularies etc. — see
expr.py); the returned `run` callable is pure and jit-traceable, and is cached
by (plan fingerprint, capacity, binding shapes) in the evaluator.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.ops.segments import (
    compact_mask,
    hash_group_order,
    lexsort_indices,
    packed_sort_indices,
    segment_aggregate,
    segment_boundaries,
    segment_arg_by,
    segment_distinct_count,
    sort_key_planes,
)
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.engine.expr import (
    BindContext,
    BoundExpr,
    ColumnBinding,
    EmitContext,
    ExprBinder,
)
from ytsaurus_tpu.schema import EValueType, TableSchema, device_dtype


@dataclass
class OutputColumn:
    name: str
    type: EValueType
    vocab: Optional[np.ndarray]


@dataclass
class PreparedQuery:
    """Host-bound execution plan for one chunk shape."""
    run: callable                  # (columns, row_valid, bindings) -> (planes, count)
    bindings: list
    output: list[OutputColumn]
    capacity: int                  # input capacity
    out_capacity: int = 0          # output plane length (≠ input for fast group)
    structure_key: tuple = ()      # host decisions that shape the program

    def binding_shapes(self) -> tuple:
        return (tuple((tuple(b.shape), str(b.dtype)) for b in self.bindings),
                self.structure_key)


import weakref

_MINMAX_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _column_min_max(col, ty: EValueType) -> tuple[int, int]:
    """Min/max of an integer column's valid values, memoized per device
    plane (two tiny reductions + host reads otherwise repeat on every
    execution of a cached plan)."""
    try:
        cached = _MINMAX_CACHE.get(col.data)
    except TypeError:
        cached = None
    if cached is not None:
        return cached
    info = np.iinfo(np.int64 if ty is EValueType.int64 else np.uint64)
    top = jnp.array(info.max, dtype=col.data.dtype)
    bot = jnp.array(info.min, dtype=col.data.dtype)
    # Both reductions cross device→host as ONE stacked transfer (the
    # `yt analyze` jax pass flagged the original two `int(jnp.min)` /
    # `int(jnp.max)` reads — two blocking syncs where one suffices).
    # analyze: allow(host-sync): the memoized min/max IS this path's one sanctioned sync
    lo_hi = np.asarray(jnp.stack(
        [jnp.min(jnp.where(col.valid, col.data, top)),
         jnp.max(jnp.where(col.valid, col.data, bot))]))
    # analyze: allow(host-sync): lo_hi is host numpy (the one stacked transfer above)
    lo, hi = int(lo_hi[0]), int(lo_hi[1])
    if hi < lo:               # no valid values at all
        lo, hi = 0, 0
    try:
        _MINMAX_CACHE[col.data] = (lo, hi)
    except TypeError:
        pass
    return lo, hi


def _column_bindings(schema: TableSchema, chunk) -> dict[str, ColumnBinding]:
    out = {}
    for col_schema in schema:
        col = chunk.columns.get(col_schema.name)
        if col is None:
            raise YtError(f"Chunk is missing column {col_schema.name!r}",
                          code=EErrorCode.QueryExecutionError)
        out[col_schema.name] = ColumnBinding(type=col_schema.type,
                                             vocab=col.dictionary)
    return out


def prepare(plan: "ir.Query | ir.FrontQuery", chunk) -> PreparedQuery:
    """Bind a plan against one chunk's vocabularies/capacity."""
    capacity = chunk.capacity
    bind_ctx = BindContext(columns=_column_bindings(plan.schema, chunk))
    binder = ExprBinder(bind_ctx)

    where_b: Optional[BoundExpr] = None
    if isinstance(plan, ir.Query) and plan.where is not None:
        where_b = binder.bind(plan.where)

    group = plan.group
    group_key_b: list[tuple[str, BoundExpr]] = []
    agg_arg_b: list[tuple[ir.AggregateItem, Optional[BoundExpr]]] = []
    post_binder: Optional[ExprBinder] = None
    having_b = None
    if group is not None:
        for item in group.group_items:
            group_key_b.append((item.name, binder.bind(item.expr)))
        for agg in group.aggregate_items:
            arg = binder.bind(agg.argument) if agg.argument is not None else None
            by_arg = binder.bind(agg.by_argument) \
                if agg.by_argument is not None else None
            agg_arg_b.append((agg, arg, by_arg))
        # Post-group namespace: keys + aggregate slots.
        post_columns: dict[str, ColumnBinding] = {}
        for (name, bound), item in zip(group_key_b, group.group_items):
            post_columns[name] = ColumnBinding(type=bound.type, vocab=bound.vocab)
        for agg, arg, _ in agg_arg_b:
            vocab = arg.vocab if (arg is not None and
                                  agg.type is EValueType.string) else None
            post_columns[agg.name] = ColumnBinding(type=agg.type, vocab=vocab)
        post_binder = ExprBinder(BindContext(columns=post_columns,
                                             bindings=bind_ctx.bindings,
                                             structure=bind_ctx.structure))
        if plan.having is not None:
            having_b = post_binder.bind(plan.having)
    final_binder = post_binder if post_binder is not None else binder

    # Window stage: binds partition/order/item expressions and registers
    # the slot columns so ORDER BY / projection can reference them.
    window = plan.window
    win_stage = None
    if window is not None:
        if group is not None:
            raise YtError("Window functions cannot combine with GROUP BY",
                          code=EErrorCode.QueryUnsupported)
        from ytsaurus_tpu.query.engine.window import WindowStage
        win_stage = WindowStage(window, binder)
        bind_ctx.columns.update(win_stage.slot_bindings())

    order_b: list[tuple[BoundExpr, bool]] = []
    if plan.order is not None:
        for item in plan.order.items:
            order_b.append((final_binder.bind(item.expr), item.descending))

    project_b: list[tuple[str, BoundExpr]] = []
    if plan.project is not None:
        for item in plan.project.items:
            project_b.append((item.name, final_binder.bind(item.expr)))
    else:
        # Identity projection over the stage's namespace.
        if group is not None:
            for (name, bound) in group_key_b:
                project_b.append((name, _post_ref(name, bound)))
            for agg, arg, _ in agg_arg_b:
                vocab = arg.vocab if (arg is not None and
                                      agg.type is EValueType.string) else None
                project_b.append((agg.name, _post_ref_t(agg.name, agg.type, vocab)))
        else:
            for col_schema in plan.schema:
                project_b.append(
                    (col_schema.name,
                     final_binder.bind(ir.TReference(type=col_schema.type,
                                                     name=col_schema.name))))
            if window is not None:
                # Identity projection carries the window slots (the
                # bottom stage of a distributed window plan).
                for item in window.items:
                    project_b.append(
                        (item.name,
                         final_binder.bind(ir.TReference(type=item.type,
                                                         name=item.name))))

    output = [OutputColumn(name=name, type=b.type, vocab=b.vocab)
              for name, b in project_b]
    offset = plan.offset
    limit = plan.limit

    # Packed-key bit widths per ORDER BY item bake into the sort
    # program (vocab-length-derived: a trace constant binding shapes
    # cannot see) — computed once here and noted into the structure key.
    order_bits = [_order_key_bits(bound) for bound, _desc in order_b]
    if order_bits:
        bind_ctx.note("obits", *order_bits)

    # Presorted-layout sort skip (ISSUE 19): tablet snapshots seal their
    # key order into chunk.sorted_by (ascending, null-first — the same
    # comparator pack_key_planes_bits encodes).  When every ORDER BY item
    # is a plain ascending column reference forming a prefix of that
    # sealed order, and no stage upstream of ORDER BY reorders rows
    # (filter only masks lanes; GROUP BY and window slots change the
    # namespace), the packed sort is the identity on valid rows: the
    # stable compact downstream yields bit-identical output without it.
    # The decision is chunk-layout-derived, so it is noted into the
    # structure key — a sealed and an unsealed chunk of the same capacity
    # must not share a compiled program.
    presorted_skip = False
    if order_b and group is None and window is None and \
            plan.order is not None and getattr(chunk, "sorted_by", ()):
        names: "list[str] | None" = []
        for item in plan.order.items:
            if isinstance(item.expr, ir.TReference) and not item.descending:
                names.append(item.expr.name)
            else:
                names = None
                break
        if names is not None and \
                tuple(names) == tuple(chunk.sorted_by)[:len(names)]:
            presorted_skip = True
            bind_ctx.note("presorted", len(names))

    # --- direct-aggregation fast path ----------------------------------------
    # When every group key has a small known value domain (dictionary codes,
    # booleans), segment ids are computed arithmetically — no sort.  This is
    # the TPU answer to the reference's open hash table in GroupOpHelper
    # (cg_routines/registry.cpp:1230): for low-cardinality keys the "hash
    # table" becomes a dense segment_sum over dict-code strides.
    fast_group = None
    if group is not None:
        # Per key: (size, offset).  Dictionary codes and booleans have known
        # domains; integer REFERENCE columns get a device min/max probe (one
        # tiny reduction, host-read) — XLA sorts collapse beyond ~4M rows on
        # TPU, so avoiding the sort is worth a probe per (chunk, plan).
        sizes_offsets: "list[tuple[int, int]] | None" = []
        for item, (_, bound) in zip(group.group_items, group_key_b):
            if bound.type is EValueType.string and bound.vocab is not None:
                sizes_offsets.append((len(bound.vocab), 0))
            elif bound.type is EValueType.boolean:
                sizes_offsets.append((2, 0))
            elif bound.type in (EValueType.int64, EValueType.uint64) and \
                    isinstance(item.expr, ir.TReference):
                col = chunk.columns.get(item.expr.name) \
                    if hasattr(chunk, "columns") else None
                data = getattr(col, "data", None)
                if data is None:          # rep chunks carry no planes
                    sizes_offsets = None
                    break
                lo, hi = _column_min_max(col, bound.type)
                if hi - lo + 1 > 65536:
                    sizes_offsets = None
                    break
                sizes_offsets.append((hi - lo + 1, lo))
            else:
                sizes_offsets = None
                break
        if sizes_offsets is not None:
            dims = 1
            for s, _ in sizes_offsets:
                dims *= s + 1          # +1 slot per key for NULL
            if 0 < dims <= 65536:
                strides = []
                acc = 1
                for s, _ in reversed(sizes_offsets):
                    strides.append(acc)
                    acc *= s + 1
                strides.reverse()
                from ytsaurus_tpu.chunks.columnar import pad_capacity
                fast_group = (tuple(sizes_offsets), tuple(strides), dims,
                              pad_capacity(dims + 1))

    # Plan auto-parameterization (ISSUE 10): OFFSET/LIMIT are static
    # residue that BUCKETS instead of hoisting — the top-k candidate
    # count must be a trace constant, so static decisions use the pow2
    # bucket (>= the actual value) while the exact offset/limit ride as
    # runtime bindings.  One program then serves every LIMIT within a
    # bucket, matching the parameterized fingerprint
    # (ir.fingerprint(omit_values=True) buckets limits the same way).
    from ytsaurus_tpu.chunks.columnar import next_pow2
    from ytsaurus_tpu.config import compile_config
    parameterized = compile_config().parameterize
    if parameterized:
        k_static = ((next_pow2(offset) if offset > 0 else 0)
                    + next_pow2(max(limit, 1))) if limit is not None \
            else None
    else:
        k_static = (offset + limit) if limit is not None else None

    # Single-key ORDER BY ... LIMIT k fast path decision (static): full
    # sorts collapse on TPU beyond a few million rows, so select ~2k
    # candidates with lax.top_k and only sort those.
    k_limit = k_static
    group_stage_cap = fast_group[3] if fast_group else capacity
    use_topk = (len(order_b) == 1 and k_limit is not None
                and 0 < k_limit <= 1024 and group_stage_cap > 4 * k_limit
                and not presorted_skip)
    topk_cand_cap = 3 * k_limit if use_topk else None

    offset_slot = limit_slot = None
    if parameterized:
        offset_slot = bind_ctx.add(jnp.asarray(np.int64(offset)))
        if limit is not None:
            limit_slot = bind_ctx.add(jnp.asarray(np.int64(limit)))

    def run(columns: dict, row_valid: jax.Array, bindings: tuple):
        ctx = EmitContext(columns=columns, bindings=bindings, capacity=capacity)
        stage_cap = capacity
        mask = row_valid
        if where_b is not None:
            d, v = where_b.emit(ctx)
            mask = mask & v & d.astype(bool)

        if group is not None and fast_group is not None:
            sizes_offsets, strides, dims, seg_cap = fast_group
            nseg = dims + 1                    # +1 garbage slot for masked rows

            def _pad(plane):
                return jnp.zeros(seg_cap, dtype=plane.dtype).at[:nseg].set(plane)

            key_planes = [b.emit(ctx) for _, b in group_key_b]
            seg = jnp.zeros(capacity, dtype=jnp.int32)
            for (data, valid), (size, key_offset), stride in zip(
                    key_planes, sizes_offsets, strides):
                if jnp.issubdtype(data.dtype, jnp.integer):
                    # Modular uint64 subtraction: correct for int64 offsets
                    # near the type bounds and uint64 keys >= 2^63.
                    off = np.uint64(key_offset % (1 << 64))
                    shifted = (data.astype(jnp.uint64) - off).astype(jnp.int32)
                else:
                    shifted = (data.astype(jnp.int64)
                               - key_offset).astype(jnp.int32)
                code = jnp.where(valid, shifted, size)
                seg = seg + code * stride
            seg = jnp.where(mask, seg, dims)   # masked-out rows → garbage slot
            # Above the dense-reduce limit the reduction needs segment-
            # sorted rows (scatter-adds serialize on TPU) — ONE u32 sort
            # here is shared by every aggregate below.
            from ytsaurus_tpu.ops.segments import presort_segments
            grp_order = presort_segments(seg, nseg)
            presorted = grp_order is not None
            if presorted:
                seg = seg[grp_order]
                gmask = mask[grp_order]
            else:
                gmask = mask

            def _r(plane):
                return plane if grp_order is None else plane[grp_order]

            present_counts, _ = segment_aggregate(
                "count", gmask, gmask, seg, nseg, EValueType.int64,
                assume_sorted=presorted)
            present = _pad((jnp.arange(nseg) < dims) & (present_counts > 0))
            new_columns: dict[str, tuple[jax.Array, jax.Array]] = {}
            slot = jnp.arange(seg_cap)
            for (name, bound), (size, key_offset), stride in zip(
                    group_key_b, sizes_offsets, strides):
                code = (slot // stride) % (size + 1)
                key_valid = code < size
                data = jnp.clip(code, 0, max(size - 1, 0))
                if bound.type is EValueType.boolean:
                    data = data.astype(jnp.bool_)
                elif bound.type in (EValueType.int64, EValueType.uint64):
                    dt = device_dtype(bound.type)
                    data = data.astype(dt) + jnp.array(key_offset, dtype=dt)
                else:
                    data = data.astype(jnp.int32)
                new_columns[name] = (data, key_valid)
            for agg, arg, by_arg in agg_arg_b:
                if agg.function == "avg":
                    data, valid = arg.emit(ctx)
                    data = _r(data).astype(jnp.float64)
                    valid = _r(valid) & gmask
                    s, sv = segment_aggregate("sum", data, valid, seg,
                                              nseg, EValueType.double,
                                              assume_sorted=presorted)
                    c, _ = segment_aggregate("count", data, valid, seg,
                                             nseg, EValueType.int64,
                                             assume_sorted=presorted)
                    new_columns[agg.name] = (_pad(s / jnp.maximum(c, 1)),
                                             _pad(sv))
                elif agg.function == "cardinality":
                    data, valid = arg.emit(ctx)
                    d, dv = segment_distinct_count(
                        _r(data), _r(valid) & gmask, seg, nseg)
                    new_columns[agg.name] = (_pad(d), _pad(dv))
                elif agg.function in ("argmin", "argmax"):
                    vd, vv = arg.emit(ctx)
                    bd, bv = by_arg.emit(ctx)
                    out_d, out_v = segment_arg_by(
                        _r(vd), _r(vv), _r(bd), _r(bv) & gmask, seg, nseg,
                        take_max=(agg.function == "argmax"),
                        assume_sorted=presorted)
                    new_columns[agg.name] = (_pad(out_d), _pad(out_v))
                else:
                    data, valid = arg.emit(ctx)
                    valid = _r(valid) & gmask
                    out, out_v = segment_aggregate(
                        agg.function, _r(data), valid, seg, nseg, agg.type,
                        assume_sorted=presorted)
                    new_columns[agg.name] = (_pad(out), _pad(out_v))
            mask = present
            stage_cap = seg_cap
            ctx = EmitContext(columns=new_columns, bindings=bindings,
                              capacity=seg_cap)
            if having_b is not None:
                d, v = having_b.emit(ctx)
                mask = mask & v & d.astype(bool)
        elif group is not None:
            key_planes = [b.emit(ctx) for _, b in group_key_b]
            # Exact grouping order: equal key tuples made adjacent via
            # the order-preserving key encoding (segments.py), masked
            # rows last; large/wide keys dispatch to the tiled radix
            # engine (ops/radix.py) instead of the one-pass network.
            order_idx = hash_group_order(key_planes, mask)
            sorted_mask = mask[order_idx]
            sorted_keys = [(d[order_idx], v[order_idx]) for d, v in key_planes]
            seg_ids, num_groups = segment_boundaries(sorted_keys, sorted_mask)
            new_columns: dict[str, tuple[jax.Array, jax.Array]] = {}
            for (name, _), (data, valid) in zip(group_key_b, sorted_keys):
                out_d, _ = segment_aggregate("first", data, sorted_mask,
                                             seg_ids, capacity,
                                             EValueType.null,
                                             assume_sorted=True)
                out_v, _ = segment_aggregate(
                    "first", valid.astype(jnp.int8), sorted_mask, seg_ids,
                    capacity, EValueType.null, assume_sorted=True)
                new_columns[name] = (out_d, out_v.astype(bool))
            for agg, arg, by_arg in agg_arg_b:
                if agg.function == "avg":
                    data, valid = arg.emit(ctx)
                    data = data[order_idx].astype(jnp.float64)
                    valid = valid[order_idx] & sorted_mask
                    s, sv = segment_aggregate("sum", data, valid, seg_ids,
                                              capacity, EValueType.double,
                                              assume_sorted=True)
                    c, _ = segment_aggregate("count", data, valid, seg_ids,
                                             capacity, EValueType.int64,
                                             assume_sorted=True)
                    cnt = jnp.maximum(c, 1)
                    new_columns[agg.name] = (s / cnt, sv)
                elif agg.function == "cardinality":
                    data, valid = arg.emit(ctx)
                    d, dv = segment_distinct_count(
                        data[order_idx], valid[order_idx] & sorted_mask,
                        seg_ids, capacity)
                    new_columns[agg.name] = (d, dv)
                elif agg.function in ("argmin", "argmax"):
                    vd, vv = arg.emit(ctx)
                    bd, bv = by_arg.emit(ctx)
                    out_d, out_v = segment_arg_by(
                        vd[order_idx], vv[order_idx],
                        bd[order_idx], bv[order_idx] & sorted_mask,
                        seg_ids, capacity,
                        take_max=(agg.function == "argmax"),
                        assume_sorted=True)
                    new_columns[agg.name] = (out_d, out_v)
                else:
                    data, valid = arg.emit(ctx)
                    data = data[order_idx]
                    valid = valid[order_idx] & sorted_mask
                    out, out_v = segment_aggregate(
                        agg.function, data, valid, seg_ids, capacity,
                        agg.type, assume_sorted=True)
                    new_columns[agg.name] = (out, out_v)
            mask = jnp.arange(capacity) < num_groups
            ctx = EmitContext(columns=new_columns, bindings=bindings,
                              capacity=capacity)
            if having_b is not None:
                d, v = having_b.emit(ctx)
                mask = mask & v & d.astype(bool)

        if win_stage is not None:
            # Window columns join the namespace; no rows move.
            win_columns = win_stage.emit(ctx, mask)
            ctx = EmitContext(columns={**ctx.columns, **win_columns},
                              bindings=bindings, capacity=stage_cap)

        if order_b and not presorted_skip:
            # Candidates = top-k by value (masked excluded) ∪ up-to-k null
            # rows (null ordering differs by direction; the tiny exact sort
            # below settles it).
            if use_topk:
                bound, descending = order_b[0]
                data, valid = bound.emit(ctx)
                value, null_key = sort_key_planes(data, valid, descending)
                # Invert the value so top_k picks the query's front.  Valid
                # rows compete by value; null rows are all equal (their
                # position relative to values is settled by the tiny exact
                # sort below), so an indicator pass covers them; a third
                # indicator pass covers valid rows whose inverted value
                # aliases the exclusion sentinel (single value class).
                if jnp.issubdtype(value.dtype, jnp.unsignedinteger):
                    inv = ~value
                elif jnp.issubdtype(value.dtype, jnp.integer) or \
                        value.dtype == jnp.bool_:
                    inv = ~value.astype(jnp.int64)
                else:
                    inv = -value.astype(jnp.float64)
                if jnp.issubdtype(inv.dtype, jnp.integer):
                    bottom = jnp.array(jnp.iinfo(inv.dtype).min, inv.dtype)
                else:
                    bottom = jnp.array(-jnp.inf, inv.dtype)
                include = mask & valid
                ranked = jnp.where(include, inv, bottom)
                _, idx1 = jax.lax.top_k(ranked, k_limit)
                nulls = (mask & ~valid).astype(jnp.int32)
                _, idx2 = jax.lax.top_k(nulls, k_limit)
                aliased = (include & (inv == bottom)).astype(jnp.int32)
                _, idx3 = jax.lax.top_k(aliased, k_limit)
                cand = jnp.concatenate([idx1, idx2, idx3])
                # Dedupe candidates (overlap would duplicate rows).
                cand_sorted = jnp.sort(cand)
                dup = jnp.concatenate([
                    jnp.zeros(1, dtype=bool),
                    cand_sorted[1:] == cand_sorted[:-1]])
                cand_cap = cand.shape[0]
                ctx = EmitContext(
                    columns={name: (d[cand_sorted], v[cand_sorted])
                             for name, (d, v) in ctx.columns.items()},
                    bindings=bindings, capacity=cand_cap)
                mask = mask[cand_sorted] & ~dup
                stage_cap = cand_cap
            # Packed composite sort key: masked-last bit + every ORDER BY
            # item (null bit + order-preserving value bits) packed into as
            # few u64 words as possible — minimum operands through the
            # device sort network (payload columns are gathered after).
            items = [((~mask), jnp.ones_like(mask), False, 1)]
            for (bound, descending), bits in zip(order_b, order_bits):
                data, valid = bound.emit(ctx)
                items.append((data, valid, descending, bits))
            order_idx = packed_sort_indices(items)
            ctx = EmitContext(
                columns={name: (d[order_idx], v[order_idx])
                         for name, (d, v) in ctx.columns.items()},
                bindings=bindings, capacity=stage_cap)
            mask = mask[order_idx]

        planes = []
        for name, bound in project_b:
            d, v = bound.emit(ctx)
            planes.append((d, v))

        # Compact valid rows to the front (stable → preserves sort order).
        comp_idx, total = compact_mask(mask)
        if offset_slot is not None:
            # Dynamic offset/limit (read from bindings): clamped to the
            # stage capacity so the downstream int32 arithmetic is safe.
            off = jnp.minimum(bindings[offset_slot],
                              stage_cap).astype(total.dtype)
        else:
            off = offset
        count = total - off
        if limit is not None:
            lim = jnp.minimum(bindings[limit_slot],
                              stage_cap).astype(total.dtype) \
                if limit_slot is not None else limit
            count = jnp.minimum(count, lim)
        count = jnp.maximum(count, 0)
        out_planes = []
        shift = jnp.clip(jnp.arange(stage_cap) + off, 0, stage_cap - 1)
        for d, v in planes:
            d = d[comp_idx][shift]
            v = v[comp_idx][shift] & (jnp.arange(stage_cap) < count)
            out_planes.append((d, v))
        return out_planes, count

    return PreparedQuery(
        run=run, bindings=bind_ctx.bindings, output=output, capacity=capacity,
        out_capacity=topk_cand_cap if use_topk else group_stage_cap,
        structure_key=((("fastgrp",) + fast_group[0] if fast_group else ())
                       + (("topk", k_limit) if use_topk else ())
                       + (("param", k_static) if parameterized else ())
                       + tuple(bind_ctx.structure)))


def _order_key_bits(bound: BoundExpr) -> int:
    """Packed-key width for one ORDER BY item: dictionary codes and bools
    need few bits; everything else is full-width."""
    if bound.type is EValueType.boolean:
        return 1
    if bound.type is EValueType.string and bound.vocab is not None:
        return max(len(bound.vocab) - 1, 1).bit_length()
    return 64


def _post_ref(name: str, bound: BoundExpr) -> BoundExpr:
    return _post_ref_t(name, bound.type, bound.vocab)


def _post_ref_t(name: str, ty: EValueType, vocab) -> BoundExpr:
    def emit(ctx: EmitContext):
        return ctx.columns[name]
    return BoundExpr(type=ty, vocab=vocab, emit=emit)
