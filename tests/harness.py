"""Query-evaluation test harness.

Port of the reference's TQueryEvaluateTest harness pattern
(library/query/unittests/evaluate/test_evaluate.h:61): evaluate(query, tables,
expected) runs parse → build → lower → execute against in-memory chunks and
compares materialized rows.  Comparison is order-insensitive unless the query
has ORDER BY (then prefix order matters).
"""

from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.query import select_rows
from ytsaurus_tpu.schema import TableSchema


def _canon(v):
    # Sortable, type-tagged canonical form (None must order against values).
    if v is None:
        return (0, 0)
    if isinstance(v, bool):
        return (1, int(v))
    if isinstance(v, float):
        return (2, round(v, 9))
    if isinstance(v, int):
        return (2, v)
    if isinstance(v, bytes):
        return (3, v)
    if isinstance(v, str):
        return (3, v.encode())
    return (4, repr(v))


def _canon_row(row: dict) -> tuple:
    return tuple((k, _canon(v)) for k, v in sorted(row.items()))


def evaluate(query, tables, expected=None, ordered=False, schemas=None):
    """tables: {path: (schema_spec, rows)} or {path: ColumnarChunk}.
    expected: list of dicts (or None to just return results)."""
    chunks = {}
    built_schemas = dict(schemas or {})
    for path, spec in tables.items():
        if isinstance(spec, ColumnarChunk):
            chunks[path] = spec
        else:
            schema_spec, rows = spec
            schema = (schema_spec if isinstance(schema_spec, TableSchema)
                      else TableSchema.make(schema_spec))
            chunks[path] = ColumnarChunk.from_rows(schema, rows)
    result = select_rows(query, chunks, schemas=built_schemas)
    rows = result.to_rows()
    if expected is not None:
        got = [_canon_row(r) for r in rows]
        want = [_canon_row(r) for r in expected]
        if ordered:
            assert got == want, f"\nquery: {query}\n got: {rows}\nwant: {expected}"
        else:
            assert sorted(got) == sorted(want), \
                f"\nquery: {query}\n got: {sorted(got)}\nwant: {sorted(want)}"
    return rows
