"""`yt analyze` — the AST-based static-analysis suite (ISSUE 9).

Six passes over one shared parse of the tree (see core.py for the
framework: finding model, waivers, baseline ratchet):

  locks     lock discipline (`# guards:` annotations) + the global
            lock-acquisition-order graph, failing on cycles
  guards    ISSUE 15: annotation-FREE lock-guard inference (RacerD-
            shaped held-set propagation with thread-entry roots and
            init-escape), check-then-act atomicity lint, and
            annotation-drift cross-checks; also exports the superset
            reconciliation graph the runtime sanitizer
            (utils/sanitizers.py) asserts its dynamic edges against
  jax       JAX tracing hazards: hidden device→host syncs in hot-path
            modules, Python branches on traced values, dynamically
            shaped calls into jitted callees
  coverage  failpoint coverage of I/O functions in the server/chunk/rpc
            planes + PR 5's span-site discipline (no interior roots)
  errors    error-taxonomy soundness: unique EErrorCode values,
            registered codes at raise sites
  sensors   PR 6's sensor-catalog lint

Entry points: `yt analyze [--pass ...] [--json] [--update-baseline]`,
`python -m tools.analyze`, and the tier-1 gate in
tests/test_static_analysis.py (repo clean against the committed
baseline — the ratchet means findings may only ever decrease).
"""

from __future__ import annotations

import inspect
from typing import Iterable, Optional

from tools.analyze import (
    coverage,
    error_taxonomy,
    guard_inference,
    jax_hazards,
    lock_discipline,
    sensors,
)
from tools.analyze.core import (
    BASELINE_PATH,
    Finding,
    SourceFile,
    aggregate,
    check_ratchet,
    load_baseline,
    load_files,
    waiver_findings,
    write_baseline,
)

__all__ = [
    "PASSES", "Finding", "SourceFile", "load_files", "run_passes",
    "load_baseline", "write_baseline", "check_ratchet", "aggregate",
    "BASELINE_PATH",
]

PASSES = {
    "locks": lock_discipline.run,
    "guards": guard_inference.run,
    "jax": jax_hazards.run,
    "coverage": coverage.run,
    "errors": error_taxonomy.run,
    "sensors": sensors.run,
}


def run_passes(files: "list[SourceFile]",
               only: Optional[Iterable[str]] = None,
               root: Optional[str] = None) -> "list[Finding]":
    """Run the selected passes (all by default) over pre-loaded files;
    framework-level waiver findings (bare waivers with no reason) are
    emitted exactly once, not per pass."""
    names = list(only) if only else list(PASSES)
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise ValueError(
            f"unknown pass(es) {unknown} — available: {sorted(PASSES)}")
    findings: list[Finding] = []
    for name in names:
        fn = PASSES[name]
        if "root" in inspect.signature(fn).parameters:
            findings.extend(fn(files, root=root))
        else:
            findings.extend(fn(files))
    findings.extend(waiver_findings("framework", files))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
