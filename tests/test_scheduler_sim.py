"""Scheduler simulator: fairness properties of the PRODUCTION strategy
math under synthetic workloads (ref yt/yt/tools/scheduler_simulator)."""

import pytest

from ytsaurus_tpu.operations.simulator import (
    SimOperation,
    SimPool,
    simulate,
)


def _flood(pool, op_id, n_jobs=200, duration=1.0, arrival=0.0):
    return SimOperation(id=op_id, pool=pool, arrival=arrival,
                        n_jobs=n_jobs, job_duration=duration)


def test_equal_weights_split_evenly():
    result = simulate(
        [SimPool("a"), SimPool("b")],
        [_flood("a", "opA"), _flood("b", "opB")],
        total_slots=8)
    ratio = result.usage_ratio("a", "b")
    assert 0.9 < ratio < 1.1, ratio
    assert result.completions["opA"] == pytest.approx(
        result.completions["opB"], rel=0.1)


def test_weights_split_proportionally():
    result = simulate(
        [SimPool("heavy", weight=2.0), SimPool("light", weight=1.0)],
        [_flood("heavy", "opH", n_jobs=400), _flood("light", "opL")],
        total_slots=9)
    # While both are saturated, heavy gets ~2x the slots.  Compare the
    # usage integrals up to the lighter pool's completion.
    t_light = result.completions["opL"]
    heavy_until = sum(
        min(s[1]["heavy"], 9) * (result.samples[i + 1][0] - s[0])
        for i, s in enumerate(result.samples[:-1]) if s[0] < t_light)
    light_until = sum(
        min(s[1]["light"], 9) * (result.samples[i + 1][0] - s[0])
        for i, s in enumerate(result.samples[:-1]) if s[0] < t_light)
    assert 1.6 < heavy_until / max(light_until, 1e-9) < 2.4


def test_min_share_guarantee_bounds_wait():
    # A tiny guaranteed pool must start work immediately even while a
    # big pool floods every slot.
    result = simulate(
        [SimPool("bulk", weight=10.0),
         SimPool("latency", min_share_ratio=0.25)],
        [_flood("bulk", "opBulk", n_jobs=500),
         _flood("latency", "opLat", n_jobs=10, arrival=5.0)],
        total_slots=8)
    assert result.wait_times["opLat"] <= 1.0 + 1e-9


def test_preemption_rescues_starving_pool():
    pools = [SimPool("a"), SimPool("b")]
    ops = [_flood("a", "opA", n_jobs=64, duration=10.0),
           _flood("b", "opB", n_jobs=8, duration=1.0, arrival=2.0)]
    with_preemption = simulate(pools, ops, total_slots=8,
                               preemption=True)
    without = simulate(pools, ops, total_slots=8, preemption=False)
    # Without preemption, b waits for a 10s job to drain; with it, b
    # starts promptly at its fair share.
    assert with_preemption.wait_times["opB"] < without.wait_times["opB"]
    assert with_preemption.preemptions > 0
    # Preempted work is requeued, never lost: everything completes.
    assert set(with_preemption.completions) == {"opA", "opB"}


def test_makespan_matches_total_work():
    # One pool, no contention: makespan == total work / slots.
    result = simulate([SimPool("only")],
                      [_flood("only", "op", n_jobs=40, duration=2.0)],
                      total_slots=8)
    assert result.makespan == pytest.approx(40 * 2.0 / 8, rel=1e-6)
    assert result.pool_usage_integral["only"] == pytest.approx(
        40 * 2.0, rel=1e-6)


def test_fifo_within_pool():
    result = simulate(
        [SimPool("p")],
        [SimOperation("first", "p", 0.0, 8, 1.0),
         SimOperation("second", "p", 0.0, 8, 1.0)],
        total_slots=4)
    assert result.wait_times["first"] <= result.wait_times["second"]
    assert result.completions["first"] <= result.completions["second"]


def test_max_running_jobs_cap():
    result = simulate(
        [SimPool("capped", max_running_jobs=2), SimPool("free")],
        [_flood("capped", "opC", n_jobs=20),
         _flood("free", "opF", n_jobs=20)],
        total_slots=8)
    for _, by_pool in result.samples:
        assert by_pool["capped"] <= 2
    assert set(result.completions) == {"opC", "opF"}


def test_unknown_pool_rejected():
    with pytest.raises(ValueError):
        simulate([SimPool("a")], [_flood("nope", "op")], total_slots=2)