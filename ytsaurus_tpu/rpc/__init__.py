"""Host RPC plane: framed multipart packets over TCP + a service/channel
layer — the control-plane half of the reference's bus/RPC split.

Ref mapping (design, not translation):
  framed multipart packets w/ per-part checksums  → rpc/packet.py
    (core/bus/tcp/packet.h:9)
  TTcpConnection multiplexing                     → rpc/connection.py
    (core/bus/tcp/connection.h)
  service method registry + concurrency limits    → rpc/server.py
    (core/rpc/service_detail.h)
  retrying channels                               → rpc/channel.py
    (core/rpc/retrying_channel.h)

The data plane deliberately does NOT ride on this: rowset movement between
devices is ICI/DCN collectives (parallel/); this bus carries metadata,
chunk blobs between hosts, and tablet commands.  Bodies are binary YSON;
bulk bytes travel as zero-copy attachment parts.
"""

from ytsaurus_tpu.rpc.channel import (
    Channel,
    FailoverChannel,
    HedgingChannel,
    RetryingChannel,
)
from ytsaurus_tpu.rpc.packet import PacketError, read_packet, write_packet
from ytsaurus_tpu.rpc.server import RpcServer, Service, rpc_method

__all__ = [
    "Channel", "FailoverChannel", "HedgingChannel", "RetryingChannel",
    "PacketError", "read_packet", "write_packet", "RpcServer", "Service",
    "rpc_method",
]
