"""Ordered tablets: append-only row logs (queue tables).

Ref: tablet_node/ordered_dynamic_store.h + queue_client consumer model
(client/queue_client/consumer_client.h).  Rows have implicit global
$row_index (append order) and $timestamp; reads are offset-based; trim drops
a prefix.  Flushing writes index-stamped columnar chunks so the on-disk form
is queryable like any static chunk.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.chunks.store import ChunkCache, FsChunkStore
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.tablet.dynamic_store import OrderedDynamicStore


def ordered_chunk_schema(schema: TableSchema) -> TableSchema:
    cols = [("$row_index", "int64", "ascending"), ("$timestamp", "int64")]
    cols += [(c.name, c.type.value) for c in schema]
    return TableSchema.make(cols)


class OrderedTablet:
    def __init__(self, schema: TableSchema, chunk_store: FsChunkStore,
                 tablet_id: str = "0",
                 chunk_cache: Optional[ChunkCache] = None):
        if schema.is_sorted:
            raise YtError("Ordered tablets require an unsorted schema",
                          code=EErrorCode.TabletNotMounted)
        self.schema = schema
        self.tablet_id = tablet_id
        self.chunk_store = chunk_store
        self.chunk_cache = chunk_cache or ChunkCache(chunk_store)
        self.store = OrderedDynamicStore(schema)
        self.chunk_ids: list[str] = []
        self.chunk_ranges: list[tuple[int, int]] = []   # [start, end) per chunk
        self.base_index = 0          # first index still in the active store
        self.trimmed_count = 0
        self.mounted = True
        self.in_memory = False
        self._lock = threading.RLock()

    # -- writes ----------------------------------------------------------------

    def append_rows(self, rows: Sequence[dict], timestamp: int) -> int:
        """Returns the $row_index of the first appended row."""
        with self._lock:
            if not self.mounted:
                raise YtError(f"Tablet {self.tablet_id} is not mounted",
                              code=EErrorCode.TabletNotMounted)
            from ytsaurus_tpu.tablet.tablet import _normalize_value
            first = self.base_index + self.store.row_count
            for row in rows:
                unknown = set(row) - {c.name for c in self.schema}
                if unknown and self.schema.strict:
                    raise YtError(f"Unknown columns {sorted(unknown)}",
                                  code=EErrorCode.QueryTypeError)
                normalized = {
                    c.name: _normalize_value(row.get(c.name), c.type)
                    for c in self.schema}
                self.store.append_row(normalized, timestamp)
            return first

    # -- flush -----------------------------------------------------------------

    def flush(self) -> Optional[str]:
        with self._lock:
            n = self.store.row_count
            if n == 0:
                return None
            rows = self.store.read(0)
            chunk_rows = []
            for row in rows:
                out = {"$row_index": self.base_index + row.pop("$row_index"),
                       "$timestamp": row.pop("$timestamp")}
                out.update(row)
                chunk_rows.append(out)
            chunk = ColumnarChunk.from_rows(
                ordered_chunk_schema(self.schema), chunk_rows)
            chunk_id = self.chunk_store.write_chunk(chunk)
            self.chunk_ids.append(chunk_id)
            if self.in_memory:
                self.chunk_cache.pin(chunk_id)
            self.chunk_ranges.append((self.base_index, self.base_index + n))
            self.base_index += n
            self.store = OrderedDynamicStore(self.schema)
            return chunk_id

    def set_in_memory(self, enabled: bool) -> None:
        with self._lock:
            self.in_memory = enabled
            for cid in self.chunk_ids:
                if enabled:
                    self.chunk_cache.pin(cid)
                else:
                    self.chunk_cache.unpin(cid)

    # -- reads -----------------------------------------------------------------

    @property
    def row_count(self) -> int:
        with self._lock:
            return self.base_index + self.store.row_count

    def read_rows(self, start_index: int = 0,
                  limit: Optional[int] = None) -> list[dict]:
        """Rows with $row_index ≥ start_index (post-trim), up to limit."""
        with self._lock:
            start_index = max(start_index, self.trimmed_count)
            end = self.row_count if limit is None else start_index + limit
            out: list[dict] = []
            for chunk_id, (lo, hi) in zip(self.chunk_ids, self.chunk_ranges):
                if hi <= start_index or lo >= end:
                    continue
                chunk = self.chunk_cache.get(chunk_id)
                for row in chunk.to_rows():
                    idx = row["$row_index"]
                    if start_index <= idx < end and idx >= self.trimmed_count:
                        out.append(row)
            if end > self.base_index:
                for row in self.store.read(
                        max(0, start_index - self.base_index)):
                    idx = self.base_index + row["$row_index"]
                    if idx >= end:
                        break
                    fixed = dict(row)
                    fixed["$row_index"] = idx
                    out.append(fixed)
            out.sort(key=lambda r: r["$row_index"])
            return out

    def trim_rows(self, trimmed_count: int) -> None:
        """Logically drop rows below `trimmed_count`; physically drop chunks
        that are entirely trimmed (ref store_trimmer)."""
        with self._lock:
            if trimmed_count > self.row_count:
                raise YtError("Cannot trim beyond the last row")
            self.trimmed_count = max(self.trimmed_count, trimmed_count)
            keep_ids, keep_ranges = [], []
            for chunk_id, (lo, hi) in zip(self.chunk_ids, self.chunk_ranges):
                if hi <= self.trimmed_count:
                    self.chunk_store.remove_chunk(chunk_id)
                    self.chunk_cache.invalidate(chunk_id)
                else:
                    keep_ids.append(chunk_id)
                    keep_ranges.append((lo, hi))
            self.chunk_ids = keep_ids
            self.chunk_ranges = keep_ranges

    def snapshot(self, timestamp: "Optional[int]" = None) -> ColumnarChunk:
        """All live rows (incl. $row_index/$timestamp) as one chunk for
        queries.  With `timestamp`, only rows whose commit $timestamp is
        ≤ it — the consistent-cut form deferred multi-tablet scans pin
        to, so every shard of an ordered table reads the SAME moment no
        matter when its snapshot supplier actually runs (the
        read_snapshot(ts) analog sorted tablets already have)."""
        rows = self.read_rows(0)
        if timestamp is not None:
            rows = [r for r in rows
                    if (r.get("$timestamp") or 0) <= timestamp]
        return ColumnarChunk.from_rows(
            ordered_chunk_schema(self.schema).to_unsorted(), rows)
