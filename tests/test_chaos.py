"""Chaos replication: cards, eras, coordinated sync cutover (VERDICT r2
#8).  Ref: chaos_server replication cards + chaos_agent era semantics.
"""

import threading

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.tablet.chaos import ChaosCoordinator, current_era, get_card

SCHEMA = TableSchema.make([
    ("key", "int64", "ascending"), ("a", "string"), ("b", "int64")],
    unique_keys=True)


def make_table(client, path):
    client.create("table", path, recursive=True,
                  attributes={"schema": SCHEMA, "dynamic": True})
    client.mount_table(path)


@pytest.fixture
def upstream(tmp_path):
    return connect(str(tmp_path / "up"))


@pytest.fixture
def downstream_root(tmp_path):
    return str(tmp_path / "down")


def _rows_of(client, path):
    out = client.select_rows(f"key, a, b FROM [{path}]")
    return sorted((r["key"], r["a"], r["b"]) for r in out)


def test_card_era_history(upstream, downstream_root):
    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r1")
    make_table(down, "//r2")
    r1 = upstream.create_table_replica(
        "//t", "//r1", cluster_root=downstream_root, mode="sync")
    r2 = upstream.create_table_replica(
        "//t", "//r2", cluster_root=downstream_root, mode="async")
    coord = ChaosCoordinator(upstream)
    assert coord.era("//t") == 1
    era = coord.switch_sync("//t", r2)
    assert era == 3                      # joint era + switched era
    card = get_card(upstream, "//t")
    assert [h["reason"] for h in card["history"]] == [
        "created", f"joint:{r2}", f"switched:{r2}"]
    # Joint era had BOTH sync (never a window without a sync replica).
    joint_modes = card["history"][1]["modes"]
    assert joint_modes[r1] == "sync" and joint_modes[r2] == "sync"
    replicas = upstream.get_table_replicas("//t")
    assert replicas[r1]["mode"] == "async"
    assert replicas[r2]["mode"] == "sync"
    # Switching to the current sync replica is a no-op.
    assert coord.switch_sync("//t", r2) == 3


def test_switch_sync_preserves_and_serves_writes(upstream,
                                                 downstream_root):
    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r1")
    make_table(down, "//r2")
    r1 = upstream.create_table_replica(
        "//t", "//r1", cluster_root=downstream_root, mode="sync")
    r2 = upstream.create_table_replica(
        "//t", "//r2", cluster_root=downstream_root, mode="async")
    upstream.insert_rows("//t", [{"key": i, "a": f"v{i}", "b": i}
                                 for i in range(20)])
    coord = ChaosCoordinator(upstream)
    coord.switch_sync("//t", r2)
    # Pre-switch rows reached r2 via the gap catch-up, with no
    # replicate_step ever run.
    assert _rows_of(down, "//r2") == _rows_of(upstream, "//t")
    # Post-switch writes land on r2 synchronously.
    upstream.insert_rows("//t", [{"key": 100, "a": "x", "b": 1}])
    assert down.lookup_rows("//r2", [(100,)]) == [
        {"key": 100, "a": b"x", "b": 1}]
    # r1 (now async) catches up via the replicator as usual.
    upstream.table_replicator.replicate_step("//t")
    assert _rows_of(down, "//r1") == _rows_of(upstream, "//t")


def test_switch_under_load_no_lost_or_duplicated_writes(upstream,
                                                        downstream_root):
    """VERDICT done-criterion: sync/async swap UNDER WRITE LOAD with no
    lost and no duplicated writes on either replica."""
    down = connect(downstream_root)
    make_table(upstream, "//t")
    make_table(down, "//r1")
    make_table(down, "//r2")
    r1 = upstream.create_table_replica(
        "//t", "//r1", cluster_root=downstream_root, mode="sync")
    r2 = upstream.create_table_replica(
        "//t", "//r2", cluster_root=downstream_root, mode="async")
    coord = ChaosCoordinator(upstream)

    n_rows = 300
    failures: list = []
    done = threading.Event()

    def writer():
        try:
            for i in range(n_rows):
                upstream.insert_rows(
                    "//t", [{"key": i, "a": f"w{i}", "b": i * 2}])
        except Exception as exc:     # noqa: BLE001 — surface in assert
            failures.append(exc)
        finally:
            done.set()

    thread = threading.Thread(target=writer)
    thread.start()
    # Swap the sync replica back and forth while the writer runs.
    for target in (r2, r1, r2, r1, r2):
        coord.switch_sync("//t", target)
        if done.is_set():
            break
    thread.join(timeout=120)
    assert not thread.is_alive() and not failures, failures
    # Drain any async tail on both replicas.
    upstream.table_replicator.replicate_step("//t")
    coord.switch_sync("//t", r1)     # forces r2's gap closed too
    upstream.table_replicator.replicate_step("//t")

    want = _rows_of(upstream, "//t")
    assert len(want) == n_rows                       # upstream complete
    got_r1 = _rows_of(down, "//r1")
    got_r2 = _rows_of(down, "//r2")
    assert got_r1 == want, "r1 lost or duplicated writes"
    assert got_r2 == want, "r2 lost or duplicated writes"
    # Era advanced once per switch phase, with full history retained.
    card = get_card(upstream, "//t")
    assert current_era(upstream, "//t") == card["history"][-1]["era"]
    assert len(card["history"]) >= 9
