"""Local-mode cluster environments (ref yt/python/yt/environment)."""

from ytsaurus_tpu.environment.local import LocalCluster

__all__ = ["LocalCluster"]
