"""Reduce / MapReduce operation kernels: key-aligned slicing + grouping.

Ref mapping:
  sorted Reduce controller   → scheduler._reduce_controller
    (controller_agent/controllers/sorted_controller.cpp:1451
     CreateReduceController — key-guarantee job slicing over sorted input)
  MapReduce controller       → scheduler._map_reduce_controller
    (controller_agent/controllers/sort_controller.cpp:5029
     CreateMapReduceController — partition → shuffle → sorted reduce)
  partition function         → stable_key_hash
    (job_proxy/partition_sort_job.cpp:43 + partitioner.cpp hash routing)

Redesign vs the reference: the reference merges sorted chunk readers with
a streaming heap and cuts jobs at teleport boundaries.  Here chunks are
columnar device planes, so the "merge" of already-sorted inputs is one
device lexsort (MXU-friendly, no host heap), and job boundaries come from
a host-side scan of the decoded key columns: stripes cut only where the
reduce key changes, which IS the reference's key guarantee (no key group
ever spans two jobs).
"""

from __future__ import annotations

import zlib
from typing import Iterator, Sequence

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.errors import EErrorCode, YtError


def decode_keys(chunk: ColumnarChunk,
                key_names: Sequence[str]) -> list[tuple]:
    """Host key tuples for slicing/grouping decisions (controller side —
    row counts here are per-operation, not per-cluster)."""
    for name in key_names:
        if name not in chunk.schema:
            raise YtError(f"No such reduce column {name!r}",
                          code=EErrorCode.QueryTypeError)
    cols = [chunk.column(name).decode(chunk.row_count)
            for name in key_names]
    return list(zip(*cols)) if cols else [() for _ in range(chunk.row_count)]


def key_change_points(keys: Sequence[tuple]) -> list[int]:
    """Indices i where keys[i] != keys[i-1] (group starts, excluding 0)."""
    return [i for i in range(1, len(keys)) if keys[i] != keys[i - 1]]


def key_aligned_ranges(keys: Sequence[tuple],
                       rows_per_job: int) -> list[tuple[int, int]]:
    """Cut [0, len(keys)) into ranges of ~rows_per_job rows whose
    boundaries fall ONLY on key changes.  A single key group larger than
    rows_per_job stays whole (the key guarantee outranks the size hint,
    as in the reference's reduce job size constraints)."""
    n = len(keys)
    if n == 0:
        return []
    ranges: list[tuple[int, int]] = []
    start = 0
    for cut in key_change_points(keys) + [n]:
        if cut - start >= rows_per_job:
            ranges.append((start, cut))
            start = cut
    if start < n:
        ranges.append((start, n))
    return ranges


def iter_groups(rows: Sequence[dict],
                key_names: Sequence[str]) -> Iterator[tuple[dict, list]]:
    """Yield (key_dict, group_rows) over key-contiguous rows — the Python
    reducer calling convention (mirrors yt.wrapper's reduce iteration)."""
    if not rows:
        return
    start = 0
    current = tuple(rows[0].get(k) for k in key_names)
    for i in range(1, len(rows)):
        key = tuple(rows[i].get(k) for k in key_names)
        if key != current:
            yield dict(zip(key_names, current)), list(rows[start:i])
            start, current = i, key
    yield dict(zip(key_names, current)), list(rows[start:])


def stable_key_hash(key: tuple) -> int:
    """Process-stable partition hash (Python's hash() is salted per
    process; revival re-partitions in a NEW process and must agree).

    Numerically equal values of different Python types (1, 1.0, True)
    compare equal under dict/tuple equality, so they must hash equal too
    — otherwise one logical key group splits across partitions."""
    parts = []
    for v in key:
        if isinstance(v, bool):
            v = int(v)
        if isinstance(v, float) and v.is_integer():
            v = int(v)
        if isinstance(v, bytes):
            parts.append(b"b" + v)
        elif isinstance(v, str):
            parts.append(b"s" + v.encode())
        elif isinstance(v, float):
            parts.append(b"f" + repr(v).encode())
        elif v is None:
            parts.append(b"n")
        else:
            parts.append(b"i" + str(v).encode())
    return zlib.crc32(b"\x00".join(parts))


def partition_rows(rows: Sequence[dict], key_names: Sequence[str],
                   partition_count: int) -> list[list[dict]]:
    """Hash-route rows to partitions by reduce key (the partition job of
    the MapReduce pipeline).  Same key → same partition, always."""
    parts: list[list[dict]] = [[] for _ in range(partition_count)]
    for row in rows:
        key = tuple(row.get(k) for k in key_names)
        parts[stable_key_hash(key) % partition_count].append(row)
    return parts


def validate_sorted_input(client, path: str,
                          required_prefix: Sequence[str]) -> None:
    """Reduce requires input sorted with reduce_by as a key prefix (ref
    sorted_controller.cpp input validation)."""
    try:
        sorted_by = client.get(path + "/@sorted_by")
    except YtError:
        sorted_by = None
    if not sorted_by:
        raise YtError(
            f"Reduce input {path!r} is not sorted; run_sort it by "
            f"{list(required_prefix)} first (or use run_map_reduce)",
            code=EErrorCode.SortOrderViolation)
    prefix = list(sorted_by)[: len(required_prefix)]
    if prefix != list(required_prefix):
        raise YtError(
            f"Reduce input {path!r} is sorted by {list(sorted_by)}, which "
            f"does not start with reduce_by {list(required_prefix)}",
            code=EErrorCode.QueryTypeError)
