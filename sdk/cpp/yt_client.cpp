// C++ SDK implementation: HTTP/1.1 over POSIX sockets, no dependencies.
#include "yt_client.hpp"

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <sstream>

namespace yt_tpu {

namespace {

class Socket {
public:
    explicit Socket(int fd) : fd_(fd) {}
    ~Socket() { if (fd_ >= 0) ::close(fd_); }
    Socket(const Socket&) = delete;
    Socket& operator=(const Socket&) = delete;
    int fd() const { return fd_; }

private:
    int fd_;
};

int ConnectTo(const std::string& host, int port) {
    struct addrinfo hints;
    std::memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    const std::string service = std::to_string(port);
    if (getaddrinfo(host.c_str(), service.c_str(), &hints, &res) != 0) {
        throw YtError(0, "cannot resolve " + host);
    }
    int fd = -1;
    for (auto* ai = res; ai != nullptr; ai = ai->ai_next) {
        fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) continue;
        if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
        ::close(fd);
        fd = -1;
    }
    freeaddrinfo(res);
    if (fd < 0) {
        throw YtError(0, "cannot connect to " + host + ":" + service);
    }
    return fd;
}

void SendAll(int fd, const std::string& data) {
    size_t sent = 0;
    while (sent < data.size()) {
        ssize_t n = ::send(fd, data.data() + sent, data.size() - sent, 0);
        if (n <= 0) throw YtError(0, "send failed");
        sent += static_cast<size_t>(n);
    }
}

std::string RecvUntilClosedOrLength(int fd) {
    std::string buf;
    char chunk[4096];
    ssize_t n;
    size_t header_end = std::string::npos;
    size_t content_length = std::string::npos;
    while ((n = ::recv(fd, chunk, sizeof(chunk), 0)) > 0) {
        buf.append(chunk, static_cast<size_t>(n));
        if (header_end == std::string::npos) {
            header_end = buf.find("\r\n\r\n");
            if (header_end != std::string::npos) {
                // Parse Content-Length from the headers (the proxy always
                // sends it).
                std::string headers = buf.substr(0, header_end);
                for (auto& c : headers) c = static_cast<char>(tolower(c));
                auto pos = headers.find("content-length:");
                if (pos != std::string::npos) {
                    content_length = static_cast<size_t>(
                        std::stoul(headers.substr(pos + 15)));
                }
            }
        }
        if (header_end != std::string::npos &&
            content_length != std::string::npos &&
            buf.size() >= header_end + 4 + content_length) {
            break;
        }
    }
    return buf;
}

}  // namespace

std::string JsonQuote(const std::string& raw) {
    std::string out = "\"";
    for (char c : raw) {
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\n': out += "\\n"; break;
            case '\r': out += "\\r"; break;
            case '\t': out += "\\t"; break;
            default:
                if (static_cast<unsigned char>(c) < 0x20) {
                    char esc[8];
                    std::snprintf(esc, sizeof(esc), "\\u%04x", c);
                    out += esc;
                } else {
                    out += c;
                }
        }
    }
    out += "\"";
    return out;
}

Client::Client(std::string host, int port, std::string user)
    : host_(std::move(host)), port_(port), user_(std::move(user)) {}

std::string Client::Request(const std::string& method,
                            const std::string& path,
                            const std::string& body) const {
    Socket sock(ConnectTo(host_, port_));
    std::ostringstream req;
    req << method << " " << path << " HTTP/1.1\r\n"
        << "Host: " << host_ << ":" << port_ << "\r\n"
        << "X-YT-User: " << user_ << "\r\n"
        << "Content-Type: application/json\r\n"
        << "Content-Length: " << body.size() << "\r\n"
        << "Connection: close\r\n\r\n"
        << body;
    SendAll(sock.fd(), req.str());
    std::string response = RecvUntilClosedOrLength(sock.fd());
    auto header_end = response.find("\r\n\r\n");
    if (header_end == std::string::npos || response.size() < 12) {
        throw YtError(0, "malformed HTTP response");
    }
    int status = std::stoi(response.substr(9, 3));
    std::string payload = response.substr(header_end + 4);
    if (status < 200 || status >= 300) {
        throw YtError(status, payload);
    }
    return payload;
}

std::string Client::Execute(const std::string& command,
                            const std::string& json_params) const {
    return Request("POST", "/api/v4/" + command, json_params);
}

std::string Client::ListCommands() const {
    return Request("GET", "/api/v4", "");
}

void Client::Create(const std::string& type, const std::string& path,
                    const std::string& attributes_json) const {
    Execute("create", "{\"type\":" + JsonQuote(type) +
                      ",\"path\":" + JsonQuote(path) +
                      ",\"recursive\":true" +
                      ",\"attributes\":" + attributes_json + "}");
}

bool Client::Exists(const std::string& path) const {
    std::string out = Execute("exists", "{\"path\":" + JsonQuote(path) + "}");
    return out.find("true") != std::string::npos;
}

std::string Client::Get(const std::string& path) const {
    return Execute("get", "{\"path\":" + JsonQuote(path) + "}");
}

void Client::Set(const std::string& path,
                 const std::string& value_json) const {
    Execute("set", "{\"path\":" + JsonQuote(path) +
                   ",\"value\":" + value_json + "}");
}

void Client::WriteTable(const std::string& path,
                        const std::string& rows_json) const {
    Execute("write_table", "{\"path\":" + JsonQuote(path) +
                           ",\"rows\":" + rows_json + "}");
}

std::string Client::ReadTable(const std::string& path) const {
    return Execute("read_table", "{\"path\":" + JsonQuote(path) + "}");
}

std::string Client::SelectRows(const std::string& query) const {
    return Execute("select_rows", "{\"query\":" + JsonQuote(query) + "}");
}

}  // namespace yt_tpu
