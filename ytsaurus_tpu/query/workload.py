"""Workload recorder + replay harness (ISSUE 8 tentpole, piece a).

Ref shape: the reference keeps a structured query log (every admitted
query with its statistics) that capacity planning and regression
hunting replay against staging clusters; the JIT-pathology study
("An Empirical Analysis of Just-in-Time Compilation in Modern
Databases", PAPERS.md) builds exactly this instrument to show how often
production plan shapes recompile.  Here every admitted query folds a
COMPACT record into a bounded workload log:

  normalized query text     literals hoisted out (`?` placeholders) so
                            one plan SHAPE is one fingerprint no matter
                            the constants — the unit auto-
                            parameterization (ROADMAP 1a) will compile
                            once;
  literal bindings          the hoisted values (typed), enough to
                            reconstruct and re-run the exact query;
  identity + outcome        pool/user, wall/compile/execute split,
                            ok/error/throttled/deadline, trace id, the
                            pow2 capacity buckets the programs compiled
                            against.

The log is sampled + bounded in memory (`config.WorkloadConfig`) with
an optional rotated on-disk JSONL tier, served via monitoring
`/workload` + orchid `/workload`, and exported/imported as a VERSIONED
capture file (`yt workload capture|export`; `load_capture` fails loudly
on an incompatible schema so `yt replay` never replays garbage).

`replay()` re-runs a captured (or `synthesize_mix`-built) mix against a
live gateway with OPEN-LOOP pacing — requests dispatch at their
scheduled offsets (recorded spacing / `speed`, or a fixed `rate`)
whether or not earlier ones finished, the honest way to measure a
serving plane under load — and reports p50/p99/p999, throttle/deadline
counts, the steady-state compile-cache hit rate (second half of the
mix), and the trace ids of the slowest queries so a bad run is
diagnosable via `/traces` without re-running.  This is the measurement
substrate the ROADMAP-1 "hit rate >= 99%" acceptance and the ROADMAP-3
macro-bench both run on.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
import time
from collections import deque
from typing import Optional, Sequence

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query.parameterize import hoist_literals
from ytsaurus_tpu.utils.profiling import Profiler
from ytsaurus_tpu.utils import sanitizers

# Bump when the record shape changes incompatibly: `load_capture` (and
# the on-disk log reader) refuse mismatched captures LOUDLY instead of
# replaying garbage (ISSUE 8 satellite).  v2: records carry the
# planner-feedback ledger field `join_est_error` (ISSUE 20) — the max
# est-vs-actual join cardinality drift of the query.
WORKLOAD_SCHEMA_VERSION = 2

# The canonical recompilation-storm SLO (ISSUE 8 tentpole, piece b):
# a ratio SLO over the per-pool compile-cache counters the evaluator
# already exports into the PR 6 history rings.  Burn rate spikes when
# misses (recompiles) eat the 1% error budget — the storm detector.
# Merge into `TelemetryConfig.slos` (optionally overriding windows):
#   TelemetryConfig(slos={"compile_storm": dict(COMPILE_STORM_SLO)})
COMPILE_STORM_SLO = {
    "kind": "ratio",
    "good_sensor": "/query/compile_cache/hits",
    "bad_sensor": "/query/compile_cache/misses",
    "objective": 0.99,
    "burn_threshold": 10.0,
}


# -- query normalization -------------------------------------------------------

# THE literal-hoisting implementation lives in query/parameterize.py
# (ISSUE 10 satellite): the workload recorder's text normalization and
# the evaluator's plan parameterization share it, so the two planes
# can never silently disagree about what "the same query shape" means.
normalize_query = hoist_literals


def render_literal(kind: str, value) -> str:
    """One hoisted literal back to QL surface syntax."""
    if kind == "string":
        s = str(value)
        escaped = s.replace("\\", "\\\\").replace('"', '\\"') \
            .replace("\n", "\\n").replace("\t", "\\t") \
            .replace("\r", "\\r").replace("\0", "\\0")
        return f'"{escaped}"'
    if kind == "uint64":
        return f"{int(value)}u"
    if kind == "double":
        return repr(float(value))
    return repr(int(value))


def substitute_literals(normalized: str, literals: Sequence) -> str:
    """Reconstruct runnable query text: literals back into the `?`
    placeholders, in order.  Counts must match exactly — a corrupt or
    hand-edited capture fails here, loudly, before anything runs."""
    parts = normalized.split("?")
    if len(parts) != len(literals) + 1:
        raise YtError(
            f"workload record is corrupt: {len(parts) - 1} placeholders "
            f"vs {len(literals)} literals in {normalized[:120]!r}",
            code=EErrorCode.InvalidConfig)
    out = [parts[0]]
    for literal, tail in zip(literals, parts[1:]):
        kind, value = literal[0], literal[1]
        out.append(render_literal(kind, value))
        out.append(tail)
    return "".join(out)


def query_fingerprint(normalized: str) -> str:
    """The workload fingerprint: one per normalized TEXT shape (the
    engine's plan fingerprint — ir.fingerprint — still varies with
    literals until ROADMAP-1 auto-parameterization lands; this is the
    shape the fleet's operators reason about)."""
    return hashlib.sha256(normalized.encode()).hexdigest()[:16]


def outcome_of(err: YtError) -> str:
    """Classify a failed query's outcome for the record."""
    if err.find(EErrorCode.RequestThrottled):
        return "throttled"
    if err.find(EErrorCode.DeadlineExceeded):
        return "deadline"
    return "error"


# -- records -------------------------------------------------------------------

_RECORD_FIELDS = (
    "kind", "query", "literals", "fingerprint", "table", "keys",
    "pool", "user", "started_at", "outcome", "wall_time",
    "compile_time", "execute_time", "rows_read", "rows_returned",
    "capacity_buckets", "trace_id", "execution_tier",
    "join_est_error",
)


class WorkloadRecord:
    """One admitted query, compactly (the workload-log unit)."""

    __slots__ = _RECORD_FIELDS

    def __init__(self, kind="select", query="", literals=(),
                 fingerprint=None, table=None, keys=0, pool=None,
                 user=None, started_at=0.0, outcome="ok", wall_time=0.0,
                 compile_time=0.0, execute_time=0.0, rows_read=0,
                 rows_returned=0, capacity_buckets=(), trace_id=None,
                 execution_tier="compiled", join_est_error=0.0):
        self.kind = kind
        self.query = query
        self.literals = [list(lit) for lit in literals]
        self.fingerprint = fingerprint or query_fingerprint(
            f"{kind}|{table or ''}|{query}")
        self.table = table
        self.keys = int(keys)
        self.pool = pool
        self.user = user
        self.started_at = float(started_at)
        self.outcome = outcome
        self.wall_time = float(wall_time)
        self.compile_time = float(compile_time)
        self.execute_time = float(execute_time)
        self.rows_read = int(rows_read)
        self.rows_returned = int(rows_returned)
        self.capacity_buckets = sorted(int(b) for b in capacity_buckets)
        self.trace_id = trace_id
        # Which tier served the query (ISSUE 18): defaults keep old
        # captures loadable — a missing field reads as "compiled".
        self.execution_tier = execution_tier
        # Planner feedback ledger (ISSUE 20): the query's max
        # est-vs-actual join cardinality drift (planner.est_drift) —
        # the per-fingerprint roll-up of this is what tells an
        # operator WHICH workload shapes the planner misestimates.
        self.join_est_error = float(join_est_error)

    def to_dict(self) -> dict:
        return {field: getattr(self, field) for field in _RECORD_FIELDS}

    @classmethod
    def from_dict(cls, data: dict) -> "WorkloadRecord":
        data = {(k.decode("utf-8") if isinstance(k, bytes) else k): v
                for k, v in (data or {}).items()}
        kwargs = {field: data[field] for field in _RECORD_FIELDS
                  if field in data and data[field] is not None}
        for key in ("kind", "query", "fingerprint", "table", "pool",
                    "user", "outcome", "trace_id", "execution_tier"):
            if isinstance(kwargs.get(key), bytes):
                kwargs[key] = kwargs[key].decode("utf-8", "replace")
        return cls(**kwargs)


# -- the bounded workload log --------------------------------------------------

class WorkloadLog:
    """Sampled, bounded retention of workload records plus an on-disk
    rotated tier (config.WorkloadConfig).  Thread-safe; one global
    instance per process plus private ones in tests."""

    LOG_NAME = "workload.jsonl"

    def __init__(self, config=None):
        self._config = config
        # guards: _records, _fingerprints, recorded_n, sampled_out_n, fingerprints_dropped_n
        self._lock = sanitizers.register_lock(
            "workload.WorkloadLog._lock")
        # Disk appends take their own lock: the in-memory fold must
        # never queue behind rotation/write I/O of the on-disk tier.
        self._io_lock = threading.Lock()
        self._records: "deque[WorkloadRecord]" = deque(maxlen=4096)
        self._fingerprints: dict[str, dict] = {}
        self.recorded_n = 0
        self.sampled_out_n = 0
        self.fingerprints_dropped_n = 0
        prof = Profiler("/workload")
        self._recorded = prof.counter("recorded")
        self._dropped = prof.counter("dropped")

    @property
    def config(self):
        if self._config is not None:
            return self._config
        from ytsaurus_tpu.config import workload_config
        return workload_config()

    # -- recording -------------------------------------------------------------

    def _admit(self, cfg) -> bool:
        """The sampling draw (one per candidate record): callers that
        pre-sample pass presampled=True to observe() so a record is
        never drawn twice."""
        if cfg.sample_rate < 1.0 and random.random() >= cfg.sample_rate:
            # Callers draw OUTSIDE the record lock; the tally still
            # needs it (the lock pass flagged the bare increment —
            # concurrent sampled-out draws would lose counts).
            with self._lock:
                self.sampled_out_n += 1
            self._dropped.increment()
            return False
        return True

    def observe(self, record: WorkloadRecord,
                presampled: bool = False) -> bool:
        cfg = self.config
        if not cfg.enabled:
            return False
        if not presampled and not self._admit(cfg):
            return False
        with self._lock:
            if self._records.maxlen != cfg.capacity:
                self._records = deque(self._records, maxlen=cfg.capacity)
            self._records.append(record)
            self.recorded_n += 1
            self._fold_fingerprint_locked(record, cfg)
        self._recorded.increment()
        if cfg.log_dir:
            self._append_disk(record, cfg)
        return True

    def _fold_fingerprint_locked(self, record: WorkloadRecord, cfg) -> None:
        entry = self._fingerprints.get(record.fingerprint)
        if entry is None:
            if len(self._fingerprints) >= cfg.fingerprint_capacity:
                self.fingerprints_dropped_n += 1
                return
            entry = self._fingerprints[record.fingerprint] = {
                "kind": record.kind, "query": record.query,
                "table": record.table, "count": 0, "ok": 0, "errors": 0,
                "throttled": 0, "deadline": 0, "wall_seconds": 0.0,
                "compile_seconds": 0.0, "last_at": 0.0,
                # ISSUE 18: how often the interpreter tier served this
                # shape — next to count and compile_seconds, the
                # promotion-value signal (runs x compile cost x delta)
                # is readable straight off the roll-up.
                "interpreted": 0, "interpreted_seconds": 0.0,
                # ISSUE 20: the planner-feedback ledger — worst join
                # cardinality misestimate seen for this shape.
                "join_est_error_max": 0.0,
            }
        entry["count"] += 1
        entry["join_est_error_max"] = max(entry["join_est_error_max"],
                                          record.join_est_error)
        if record.execution_tier == "interpreted":
            entry["interpreted"] += 1
            entry["interpreted_seconds"] += record.execute_time
        bucket = record.outcome if record.outcome in (
            "ok", "throttled", "deadline") else "errors"
        entry[bucket] += 1
        entry["wall_seconds"] += record.wall_time
        entry["compile_seconds"] += record.compile_time
        entry["last_at"] = max(entry["last_at"], record.started_at)

    # The observe_* helpers are the fold sites the planes call; each is
    # one config read when the recorder is disabled.

    def observe_select(self, query: str, profile=None, stats=None,
                       outcome: str = "ok",
                       wall_time: Optional[float] = None,
                       pool: Optional[str] = None,
                       user: Optional[str] = None,
                       trace_id: Optional[str] = None) -> bool:
        cfg = self.config
        if not cfg.enabled:
            return False
        # Sample BEFORE normalizing: at sample_rate 0.01 the 99% of
        # selects that are drawn out must pay one RNG draw, not a full
        # lexer pass over the query text.
        if not self._admit(cfg):
            return False
        try:
            normalized, literals = normalize_query(query)
        except YtError:
            # Unlexable text (error-outcome records): keep it verbatim
            # so the failure is still visible in the workload.
            normalized, literals = query[:500], []
        stats_dict = {}
        if profile is not None:
            stats_dict = profile.statistics or {}
            wall_time = profile.wall_time
            pool = pool or profile.pool
            user = user or profile.user
            trace_id = trace_id or profile.trace_id
        elif stats is not None:
            stats_dict = stats.to_dict()
        from ytsaurus_tpu.query.planner import est_drift
        join_est_error = max(
            [est_drift(e.get("est_rows", 0), e.get("actual_rows", 0))
             for e in (stats_dict.get("join_plan") or []) if e] or [0.0])
        record = WorkloadRecord(
            kind="select", query=normalized, literals=literals,
            fingerprint=query_fingerprint(normalized), pool=pool,
            user=user, started_at=time.time(), outcome=outcome,
            wall_time=wall_time or 0.0,
            compile_time=float(stats_dict.get("compile_time", 0.0)),
            execute_time=float(stats_dict.get("execute_time", 0.0)),
            rows_read=int(stats_dict.get("rows_read", 0)),
            rows_returned=int(stats_dict.get("rows_written", 0)),
            capacity_buckets=stats_dict.get("capacity_buckets") or (),
            trace_id=trace_id,
            execution_tier=stats_dict.get("execution_tier", "compiled"),
            join_est_error=join_est_error)
        return self.observe(record, presampled=True)

    def observe_lookup(self, table: str, keys: Sequence[tuple],
                       outcome: str = "ok", wall_time: float = 0.0,
                       pool: Optional[str] = None,
                       user: Optional[str] = None,
                       trace_id: Optional[str] = None) -> bool:
        cfg = self.config
        if not cfg.enabled:
            return False
        if not self._admit(cfg):
            return False
        keys = [tuple(k) for k in keys]
        shape = ",".join(type(v).__name__ for v in keys[0]) if keys \
            else ""
        retained = [["key", list(k)] for k in
                    keys[:cfg.lookup_keys_per_record]]
        record = WorkloadRecord(
            kind="lookup", query=f"LOOKUP [{table}] ({shape})",
            literals=retained,
            fingerprint=query_fingerprint(f"lookup|{table}|{shape}"),
            table=table, keys=len(keys), pool=pool, user=user,
            started_at=time.time(), outcome=outcome,
            wall_time=wall_time)
        return self.observe(record, presampled=True)

    # -- the on-disk tier ------------------------------------------------------

    def _append_disk(self, record: WorkloadRecord, cfg) -> None:
        try:
            with self._io_lock:
                os.makedirs(cfg.log_dir, exist_ok=True)
                path = os.path.join(cfg.log_dir, self.LOG_NAME)
                if os.path.exists(path) and \
                        os.path.getsize(path) >= cfg.rotate_bytes:
                    self._rotate(path, cfg)
                fresh = not os.path.exists(path)
                with open(path, "a", encoding="utf-8") as f:
                    if fresh:
                        f.write(json.dumps(
                            {"workload_schema":
                             WORKLOAD_SCHEMA_VERSION}) + "\n")
                    f.write(json.dumps(record.to_dict(),
                                       default=_json_default) + "\n")
        except OSError:
            # Disk tier is best-effort observability; the in-memory log
            # stays authoritative.
            pass

    def _rotate(self, path: str, cfg) -> None:
        oldest = f"{path}.{cfg.max_files - 1}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(cfg.max_files - 2, 0, -1):
            src = f"{path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{path}.{i + 1}")
        os.replace(path, f"{path}.1")

    def read_disk_log(self,
                      log_dir: Optional[str] = None) -> list[WorkloadRecord]:
        """Every record in the rotated on-disk tier, oldest first; each
        file's header version is checked (mismatch raises)."""
        cfg = self.config
        log_dir = log_dir or cfg.log_dir
        if not log_dir:
            return []
        base = os.path.join(log_dir, self.LOG_NAME)
        paths = [f"{base}.{i}" for i in range(cfg.max_files - 1, 0, -1)]
        paths.append(base)
        out: list[WorkloadRecord] = []
        for path in paths:
            if not os.path.exists(path):
                continue
            with open(path, encoding="utf-8") as f:
                header = json.loads(f.readline() or "{}")
                _check_schema(header, path)
                for line in f:
                    if line.strip():
                        out.append(WorkloadRecord.from_dict(
                            json.loads(line)))
        return out

    # -- capture export/import -------------------------------------------------

    def export_capture(self, path: str,
                       limit: Optional[int] = None) -> int:
        """Write the retained records as a versioned capture file; the
        artifact `yt replay` and `bench.py --config replay` consume."""
        return write_capture(path, self.records(), limit=limit)

    def import_capture(self, path: str) -> int:
        records = load_capture(path)
        for record in records:
            # A deliberately imported capture keeps every record — the
            # sampling draw already happened when it was recorded.
            self.observe(record, presampled=True)
        return len(records)

    # -- views -----------------------------------------------------------------

    def records(self) -> list[WorkloadRecord]:
        with self._lock:
            return list(self._records)

    def fingerprints(self, top: int = 50) -> list[dict]:
        with self._lock:
            entries = [{"fingerprint": fp, **entry}
                       for fp, entry in self._fingerprints.items()]
        entries.sort(key=lambda e: (-e["count"], e["fingerprint"]))
        return entries[:top] if top else entries

    def snapshot(self, limit: int = 128) -> dict:
        """limit=0 serves every retained record (bounded by capacity)."""
        records = self.records()
        if limit:
            records = records[-limit:]
        return {
            "schema_version": WORKLOAD_SCHEMA_VERSION,
            "recorded": self.recorded_n,
            "sampled_out": self.sampled_out_n,
            "fingerprints_dropped": self.fingerprints_dropped_n,
            "records": [r.to_dict() for r in records],
            "fingerprints": self.fingerprints(),
        }

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._fingerprints.clear()
            self.recorded_n = 0
            self.sampled_out_n = 0
            self.fingerprints_dropped_n = 0


def _check_schema(header: dict, path: str) -> None:
    version = (header or {}).get("workload_schema")
    if version != WORKLOAD_SCHEMA_VERSION:
        raise YtError(
            f"incompatible workload capture {path!r}: schema version "
            f"{version!r}, this build speaks {WORKLOAD_SCHEMA_VERSION} "
            "— refusing to replay it",
            code=EErrorCode.InvalidConfig)


def write_capture(path: str, records: Sequence[WorkloadRecord],
                  limit: Optional[int] = None) -> int:
    """THE capture writer (WorkloadLog.export_capture and `yt workload
    capture|export` both route here): versioned header, atomic
    tmp-then-replace so a crash mid-write never leaves a truncated
    capture at the target path."""
    records = list(records)
    if limit:
        records = records[-limit:]
    payload = {
        "workload_schema": WORKLOAD_SCHEMA_VERSION,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
        "records": [r.to_dict() for r in records],
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, default=_json_default)
    os.replace(tmp, path)
    return len(records)


def load_capture(path: str) -> list[WorkloadRecord]:
    """Read a capture file, FAILING LOUDLY on an incompatible schema
    (the versioned-workload-log check: `yt replay` must never replay a
    capture whose record shape it misreads)."""
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError) as exc:
        raise YtError(f"cannot read workload capture {path!r}: {exc}",
                      code=EErrorCode.InvalidConfig)
    _check_schema(payload, path)
    return [WorkloadRecord.from_dict(r)
            for r in payload.get("records") or []]


def _json_default(value):
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    return str(value)


# -- synthetic mixes -----------------------------------------------------------

def synthesize_mix(shapes: Sequence[str], count: int = 100,
                   distinct: int = 16, seed: int = 0,
                   interval: float = 0.01,
                   pool: Optional[str] = None) -> list[WorkloadRecord]:
    """Build a parameterized-query mix without a capture: `shapes` are
    format strings with `{}` literal slots; each synthesized query draws
    its literals from a `distinct`-sized value set (Zipf-ish: low values
    dominate, like production key skew) so the mix exercises exactly the
    repeated-shape/varied-literal traffic ROADMAP 1 must compile once."""
    rng = random.Random(seed)
    records = []
    for i in range(count):
        shape = shapes[i % len(shapes)]
        n_slots = shape.count("{}")
        values = []
        for _ in range(n_slots):
            # Skewed draw: half the traffic hits the 4 hottest values.
            pick = rng.randrange(distinct) if rng.random() < 0.5 \
                else rng.randrange(max(distinct // 4, 1))
            values.append(pick)
        normalized, literals = normalize_query(shape.format(*values))
        records.append(WorkloadRecord(
            kind="select", query=normalized, literals=literals,
            fingerprint=query_fingerprint(normalized), pool=pool,
            started_at=i * interval, outcome="ok"))
    return records


# -- replay --------------------------------------------------------------------

def _decode(value):
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    if isinstance(value, dict):
        return {_decode(k): _decode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_decode(v) for v in value]
    return value


def _profile_info(profile) -> tuple[Optional[str], dict]:
    """(trace_id, statistics) from an ExecutionProfile object (in-
    process client) or its dict form (remote client)."""
    if hasattr(profile, "statistics"):
        return profile.trace_id, profile.statistics or {}
    if isinstance(profile, dict):
        d = _decode(profile)
        return d.get("trace_id"), d.get("statistics") or {}
    return None, {}


def replay(client, records: Sequence[WorkloadRecord],
           speed: float = 1.0, rate: Optional[float] = None,
           max_workers: int = 16, pool: Optional[str] = None,
           timeout: Optional[float] = None,
           limit: Optional[int] = None,
           slowest: int = 5) -> dict:
    """Re-run a workload against a live client/gateway, open-loop.

    Pacing: each record dispatches at its scheduled offset — recorded
    inter-arrival spacing divided by `speed`, or a fixed `rate` (qps)
    when given (also the fallback when the capture carries no
    timestamps).  Dispatch does NOT wait for earlier queries: a slow
    server accumulates in-flight work exactly as production would
    (bounded by `max_workers` executing threads; the backlog past that
    is measured as latency, which is the point).

    Selects run with explain_analyze=True so every replayed query
    carries its compile/execute split and trace id; the report's
    steady-state compile-cache hit rate is computed over the SECOND
    half of the mix (the first half is warmup — cold compiles are
    expected there) and the slowest queries embed their trace ids for
    `/traces` follow-up."""
    from concurrent.futures import ThreadPoolExecutor

    records = list(records)
    if limit:
        records = records[:limit]
    if not records:
        raise YtError("workload replay: no records to replay",
                      code=EErrorCode.InvalidConfig)
    # Scheduled offsets, seconds from replay start.
    if rate is not None and rate > 0:
        offsets = [i / rate for i in range(len(records))]
    else:
        base = records[0].started_at
        spread = records[-1].started_at - base
        if spread > 0:
            offsets = [(r.started_at - base) / max(speed, 1e-9)
                       for r in records]
        else:
            offsets = [0.0] * len(records)

    lock = threading.Lock()
    latencies: list[float] = []
    outcomes = {"ok": 0, "error": 0, "throttled": 0, "deadline": 0}
    steady = {"hits": 0, "misses": 0, "disk_hits": 0}
    total = {"hits": 0, "misses": 0, "disk_hits": 0}
    slow_heap: list[tuple[float, dict]] = []
    steady_from = len(records) // 2

    def run_one(idx: int, rec: WorkloadRecord) -> None:
        t0 = time.perf_counter()
        outcome = "ok"
        trace_id = None
        stats: dict = {}
        query_text = rec.query
        try:
            if rec.kind == "lookup":
                keys = [tuple(lit[1]) for lit in rec.literals
                        if lit and lit[0] == "key"]
                if keys:
                    client.lookup_rows(rec.table, keys,
                                       pool=pool or rec.pool,
                                       timeout=timeout)
            else:
                query_text = substitute_literals(rec.query, rec.literals)
                profile = client.select_rows(
                    query_text, pool=pool or rec.pool, timeout=timeout,
                    explain_analyze=True)
                trace_id, stats = _profile_info(profile)
        except YtError as err:
            outcome = outcome_of(err)
        except Exception:   # noqa: BLE001 — a replay worker must never
            # lose a query from the report: transport/driver surprises
            # count as errors, they don't silently vanish into an
            # unchecked future.
            outcome = "error"
        elapsed = time.perf_counter() - t0
        with lock:
            outcomes[outcome] += 1
            latencies.append(elapsed)
            hits = int(stats.get("cache_hits", 0))
            misses = int(stats.get("compile_count", 0))
            disk_hits = int(stats.get("compile_disk_hit", 0))
            total["hits"] += hits
            total["misses"] += misses
            total["disk_hits"] += disk_hits
            if idx >= steady_from:
                steady["hits"] += hits
                steady["misses"] += misses
                steady["disk_hits"] += disk_hits
            slow_heap.append((elapsed, {
                "query": query_text[:200],
                "fingerprint": rec.fingerprint,
                "wall_ms": round(elapsed * 1e3, 3),
                "outcome": outcome,
                "trace_id": trace_id,
            }))
            if len(slow_heap) > max(slowest, 1) * 4:
                slow_heap.sort(key=lambda e: -e[0])
                del slow_heap[max(slowest, 1) * 4:]

    t_start = time.perf_counter()
    with ThreadPoolExecutor(max_workers=max_workers,
                            thread_name_prefix="replay") as executor:
        for idx, (rec, offset) in enumerate(zip(records, offsets)):
            delay = t_start + offset - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            # Open loop: submit on schedule regardless of completions.
            executor.submit(run_one, idx, rec)
    elapsed = time.perf_counter() - t_start

    latencies.sort()

    def pct(q: float) -> float:
        if not latencies:
            return 0.0
        idx = min(int(q * len(latencies)), len(latencies) - 1)
        return latencies[idx]

    def hit_rate(bucket: dict) -> Optional[float]:
        events = bucket["hits"] + bucket["misses"]
        return round(bucket["hits"] / events, 6) if events else None

    slow_heap.sort(key=lambda e: -e[0])
    offered = (len(records) - 1) / offsets[-1] if offsets[-1] > 0 \
        else None
    return {
        "queries": len(records),
        **outcomes,
        "elapsed_seconds": round(elapsed, 6),
        "offered_rate": round(offered, 3) if offered else None,
        "achieved_rate": round(len(records) / elapsed, 3)
        if elapsed > 0 else None,
        "latency": {
            "p50_ms": round(pct(0.50) * 1e3, 3),
            "p99_ms": round(pct(0.99) * 1e3, 3),
            "p999_ms": round(pct(0.999) * 1e3, 3),
            "max_ms": round(latencies[-1] * 1e3, 3) if latencies
            else 0.0,
        },
        "compile_cache": {
            **{k: v for k, v in total.items()},
            # Misses the persistent tier served (deserialize, no
            # compile) vs programs actually built: the restart-warm-
            # start acceptance reads fresh_compiles ~ 0 (ISSUE 10).
            "fresh_compiles": total["misses"] - total["disk_hits"],
            "hit_rate": hit_rate(total),
            "steady_hits": steady["hits"],
            "steady_misses": steady["misses"],
            "steady_disk_hits": steady["disk_hits"],
            "steady_fresh_compiles":
                steady["misses"] - steady["disk_hits"],
            "steady_hit_rate": hit_rate(steady),
        },
        "slowest": [entry for _t, entry in slow_heap[:max(slowest, 1)]],
    }


# -- globals -------------------------------------------------------------------

_global_log: Optional[WorkloadLog] = None
# guards: _global_log
_log_lock = sanitizers.register_lock("workload._log_lock", hot=False)


def get_workload_log() -> WorkloadLog:
    global _global_log
    if _global_log is None:
        with _log_lock:
            if _global_log is None:
                _global_log = WorkloadLog()
    return _global_log


def configure(cfg) -> None:
    """Rebind the global log to a new workload config (called by
    config.set_workload_config; None restores lazy defaults)."""
    global _global_log
    with _log_lock:
        _global_log = None if cfg is None else WorkloadLog(cfg)
