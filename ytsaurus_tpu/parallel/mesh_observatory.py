"""Mesh execution observatory (ISSUE 20): the roll-up behind /mesh.

Whole-plan fusion (ISSUEs 10/12/14/19) collapsed every distributed
query into ONE ``jit(shard_map)`` program at exactly one host sync —
and made the inside of a query a black box.  This module is the bounded
per-fingerprint memory of what those programs measured about
themselves:

- the RUNTIME telemetry block each fused program computes on device and
  returns stacked WITH its result (``whole_plan.MESH_TELEMETRY_VERSION``
  — per-shard input/output rows, all_to_all transfer matrices, quota
  demand vs granted) plus the same-shape blocks the stitched rungs
  assemble from host values they already read;
- the COMPILE-TIME ``memory_analysis()``/``cost_analysis()`` capture
  per SPMD executable (peak temp/argument/output bytes, FLOPs — the
  buffer-donation savings of ISSUE 19 become measurable numbers).

Shape mirrors query/engine/evaluator.CompileObservatory: one sanitized
lock, bounded OrderedDict roll-ups, ``totals()/top()/snapshot()`` views
serving monitoring ``/mesh``, the orchid twin, and ``yt mesh top``.
Sensors fold under ``/query/mesh/*`` so the telemetry rings (ISSUE 6)
can burn a skew SLO against them — the observability layer the fused
sort (ROADMAP item 5) inherits for free.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from ytsaurus_tpu.utils import sanitizers
from ytsaurus_tpu.utils.profiling import Profiler

# /query/mesh sensor family: gauges track the LAST executed program's
# shape (dashboards overlay them on the history rings), counters
# accumulate exchange traffic and the balanced-vs-skewed split the
# MESH_SKEW_SLO burns against.
_mesh_profiler = Profiler("/query/mesh")
_skew_gauge = _mesh_profiler.gauge("skew_max")
_headroom_gauge = _mesh_profiler.gauge("quota_headroom")
_watermark_gauge = _mesh_profiler.gauge("memory_watermark_bytes")
_exchange_bytes_counter = _mesh_profiler.counter("exchange_bytes")
_balanced_counter = _mesh_profiler.counter("balanced")
_skewed_counter = _mesh_profiler.counter("skewed")

# Skew burn-rate SLO (satellite of ISSUE 20, the COMPILE_STORM_SLO
# idiom): "≥ `objective` of mesh program executions stay under
# TelemetryConfig.mesh_max_imbalance shard imbalance", evaluated by
# utils/slo.SloTracker over the /query/mesh balanced/skewed counters.
MESH_SKEW_SLO = {
    "kind": "ratio",
    "good_sensor": "/query/mesh/balanced",
    "bad_sensor": "/query/mesh/skewed",
    "objective": 0.99,
    "burn_threshold": 10.0,
}


def memory_analysis_dict(compiled) -> Optional[dict]:
    """Normalized ``compiled.memory_analysis()``: the byte-sized
    attributes as a plain dict, or None when the backend offers
    nothing (CPU builds vary by jax version — absence is not an
    error)."""
    try:
        mem = compiled.memory_analysis()
    except Exception:   # noqa: BLE001 — backend-dependent, optional
        return None
    if mem is None:
        return None
    out: dict = {}
    for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                 "output_size_in_bytes", "alias_size_in_bytes",
                 "generated_code_size_in_bytes"):
        val = getattr(mem, attr, None)
        if isinstance(val, (int, float)):
            out[attr] = int(val)
    if not out and isinstance(mem, dict):
        out = {k: int(v) for k, v in mem.items()
               if isinstance(v, (int, float))}
    return out or None


def peak_bytes(memory: Optional[dict]) -> Optional[int]:
    """The memory watermark of one executable: live temp + argument +
    output bytes (the residency XLA actually holds at once; donation
    savings show up here as a smaller argument+temp sum)."""
    if not memory:
        return None
    total = sum(memory.get(k, 0) for k in
                ("temp_size_in_bytes", "argument_size_in_bytes",
                 "output_size_in_bytes"))
    return int(total) if total > 0 else None


_TOP_FIELDS = {
    "skew": "skew_max",
    "bytes": "exchange_bytes",
    "memory": "memory_watermark_bytes",
    "executions": "executions",
    "drift": "drift_max",
}


class MeshObservatory:
    """Bounded per-fingerprint roll-up of mesh telemetry blocks plus the
    per-program-key compile-time memory/cost capture."""

    PROGRAM_CAP = 256       # distinct plan fingerprints retained
    COMPILED_CAP = 512      # distinct SPMD program keys retained

    def __init__(self):
        # guards: _programs, _compiled, executions_n, balanced_n, skewed_n
        self._lock = sanitizers.register_lock(
            "mesh_observatory.MeshObservatory._lock")
        self._programs: "OrderedDict[str, dict]" = OrderedDict()
        self._compiled: "OrderedDict[tuple, dict]" = OrderedDict()
        self.executions_n = 0
        self.balanced_n = 0
        self.skewed_n = 0

    # -- compile-time capture --------------------------------------------------

    def record_compile(self, key: tuple, memory: Optional[dict],
                       cost: Optional[dict]) -> None:
        """One SPMD executable's compile-time analyses, keyed by its
        program cache key (what the dispatch site holds at decode
        time)."""
        entry = {"memory": memory, "peak_bytes": peak_bytes(memory),
                 "flops": (cost or {}).get("flops"),
                 "bytes_accessed": (cost or {}).get(
                     "bytes accessed", (cost or {}).get("bytes_accessed"))}
        with self._lock:
            self._compiled[key] = entry
            while len(self._compiled) > self.COMPILED_CAP:
                self._compiled.popitem(last=False)

    def memory_for(self, key: tuple) -> Optional[int]:
        """Peak device bytes of the executable behind `key` (None when
        the backend reported no memory analysis)."""
        with self._lock:
            entry = self._compiled.get(key)
        return entry["peak_bytes"] if entry is not None else None

    # -- runtime blocks --------------------------------------------------------

    def record_execution(self, fingerprint: str, block: dict) -> None:
        """Fold one executed program's telemetry block (fused or
        stitched — same shape, see whole_plan._mesh_block) into the
        per-fingerprint roll-up + the /query/mesh sensors."""
        from ytsaurus_tpu.config import telemetry_config
        max_imbalance = telemetry_config().mesh_max_imbalance
        skew = float(block.get("skew", 1.0))
        xbytes = int(block.get("exchange_bytes", 0))
        headroom = max([float(e.get("headroom", 0.0))
                        for e in block.get("exchanges", ())] or [0.0])
        watermark = block.get("memory_watermark_bytes")
        drift = max([float(s.get("drift", 0.0))
                     for s in block.get("stages", ())] or [0.0])
        out_rows = block.get("out_rows") or ()
        skewed = int(block.get("shards", 1)) > 1 and sum(out_rows) > 0 \
            and skew > max_imbalance
        with self._lock:
            self.executions_n += 1
            if skewed:
                self.skewed_n += 1
            else:
                self.balanced_n += 1
            entry = self._programs.get(fingerprint)
            if entry is None:
                entry = self._programs[fingerprint] = {
                    "executions": 0, "skew_max": 0.0, "skew_last": 0.0,
                    "exchange_bytes": 0, "rows_out": 0,
                    "quota_headroom": 0.0, "drift_max": 0.0,
                    "memory_watermark_bytes": 0, "skewed": 0,
                    "path": block.get("path", "fused"),
                    "shards": int(block.get("shards", 0)),
                    "last_block": None,
                }
            self._programs.move_to_end(fingerprint)
            entry["executions"] += 1
            entry["skew_last"] = skew
            entry["skew_max"] = max(entry["skew_max"], skew)
            entry["exchange_bytes"] += xbytes
            entry["rows_out"] += int(sum(out_rows))
            entry["quota_headroom"] = headroom
            entry["drift_max"] = max(entry["drift_max"], drift)
            if watermark:
                entry["memory_watermark_bytes"] = max(
                    entry["memory_watermark_bytes"], int(watermark))
            if skewed:
                entry["skewed"] += 1
            entry["path"] = block.get("path", entry["path"])
            entry["last_block"] = block
            while len(self._programs) > self.PROGRAM_CAP:
                self._programs.popitem(last=False)
        _skew_gauge.set(skew)
        _headroom_gauge.set(headroom)
        if watermark:
            _watermark_gauge.set(int(watermark))
        if xbytes:
            _exchange_bytes_counter.increment(xbytes)
        if skewed:
            _skewed_counter.increment()
        else:
            _balanced_counter.increment()

    # -- views -----------------------------------------------------------------

    def totals(self) -> dict:
        with self._lock:
            return {"executions": self.executions_n,
                    "balanced": self.balanced_n,
                    "skewed": self.skewed_n,
                    "programs": len(self._programs),
                    "compiled": len(self._compiled)}

    def top(self, n: int = 20, by: str = "skew") -> list[dict]:
        """Programs ranked by `by` (skew | bytes | memory | executions |
        drift, or any numeric roll-up field)."""
        field = _TOP_FIELDS.get(by, by)
        with self._lock:
            rows = [{"fingerprint": fp,
                     **{k: v for k, v in entry.items()
                        if k != "last_block"}}
                    for fp, entry in self._programs.items()]
        rows.sort(key=lambda r: (-float(r.get(field) or 0.0),
                                 r["fingerprint"]))
        return rows[:n] if n else rows

    def snapshot(self, top: int = 50) -> dict:
        with self._lock:
            blocks = {fp: entry["last_block"]
                      for fp, entry in self._programs.items()
                      if entry["last_block"] is not None}
        return {"totals": self.totals(),
                "programs": self.top(top),
                "last_blocks": blocks,
                "slo": dict(MESH_SKEW_SLO)}

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()
            self._compiled.clear()
            self.executions_n = 0
            self.balanced_n = 0
            self.skewed_n = 0


_mesh_observatory = MeshObservatory()


def get_mesh_observatory() -> MeshObservatory:
    return _mesh_observatory
