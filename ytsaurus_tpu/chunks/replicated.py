"""Replicated chunk store: N locations, read fallback, write-back repair.

Ref: the data-node/master replication pair (server/master/chunk_server/
chunk_replicator.h issuing Replicate/Repair jobs; replication_reader.cpp
falling back across replicas).  Collapsed to one process: a chunk writes to
`replication_factor` locations; reads try locations in order and, after a
successful read, re-replicate to locations that lost their copy (the
repair-on-read analog of the replicator's background jobs).  Erasure-coded
writes pass through to a single location (parity already provides
redundancy).
"""

from __future__ import annotations

import hashlib
from typing import Optional

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.chunks.encoding import DEFAULT_CODEC
from ytsaurus_tpu.chunks.store import FsChunkStore, new_chunk_id
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils.logging import get_logger, log_event

import logging as _logging


class ReplicatedChunkStore:
    """Drop-in FsChunkStore replacement spanning several directories."""

    def __init__(self, roots: list[str], replication_factor: int = 2,
                 codec: str = DEFAULT_CODEC):
        if not roots:
            raise YtError("ReplicatedChunkStore needs at least one location")
        self.locations = [FsChunkStore(root, codec=codec) for root in roots]
        self.replication_factor = min(replication_factor, len(self.locations))
        self.codec = codec
        self._log = get_logger("ChunkReplicator")

    # -- placement -------------------------------------------------------------

    def _placement(self, chunk_id: str) -> list[FsChunkStore]:
        """Deterministic location order per chunk (rendezvous hashing with a
        process-independent hash — python's hash() is salted per process and
        would make replicas drift across restarts)."""
        def rank(i: int) -> bytes:
            return hashlib.sha256(f"{chunk_id}:{i}".encode()).digest()
        ranked = sorted(range(len(self.locations)), key=rank)
        return [self.locations[i] for i in ranked]

    # -- FsChunkStore surface --------------------------------------------------

    def write_chunk(self, chunk: ColumnarChunk,
                    chunk_id: Optional[str] = None,
                    codec: Optional[str] = None,
                    erasure: Optional[str] = None) -> str:
        chunk_id = chunk_id or new_chunk_id()
        placement = self._placement(chunk_id)
        if erasure is not None:
            placement[0].write_chunk(chunk, chunk_id=chunk_id, codec=codec,
                                     erasure=erasure)
            return chunk_id
        written = 0
        errors = []
        for store in placement:
            if written >= self.replication_factor:
                break
            try:
                store.write_chunk(chunk, chunk_id=chunk_id, codec=codec)
                written += 1
            except OSError as e:          # location down/full
                errors.append(e)
                log_event(self._log, _logging.WARNING, "replica_write_failed",
                          chunk_id=chunk_id, location=store.root,
                          error=str(e))
        if written == 0:
            raise YtError(f"All locations failed writing chunk {chunk_id}",
                          code=EErrorCode.ChunkFormatError,
                          attributes={"errors": [str(e) for e in errors]})
        if written < self.replication_factor:
            log_event(self._log, _logging.WARNING, "chunk_under_replicated",
                      chunk_id=chunk_id, replicas=written,
                      target=self.replication_factor)
        return chunk_id

    def read_chunk(self, chunk_id: str) -> ColumnarChunk:
        placement = self._placement(chunk_id)
        last_error: Optional[Exception] = None
        for store in placement:
            try:
                chunk = store.read_chunk(chunk_id)
            except (YtError, OSError) as e:   # missing OR dying location
                last_error = e
                continue
            import os
            is_erasure = os.path.exists(store._erasure_meta_path(chunk_id))
            if not is_erasure:
                # Erasure chunks carry their own redundancy; replicating
                # them in full would defeat the coding's storage savings.
                self._maybe_repair(chunk_id, chunk, placement)
            return chunk
        if isinstance(last_error, YtError):
            raise last_error
        raise YtError(f"No such chunk {chunk_id}",
                      code=EErrorCode.NoSuchChunk,
                      attributes={"last_error": str(last_error)
                                  if last_error else None})

    def _maybe_repair(self, chunk_id: str, chunk: ColumnarChunk,
                      placement: list[FsChunkStore]) -> None:
        """Top up to replication_factor TOTAL copies (counting copies on any
        location — a write that spilled past a failed location must not be
        re-replicated into over-replication when it recovers)."""
        holders = [s for s in placement if s.exists(chunk_id)]
        missing = self.replication_factor - len(holders)
        if missing <= 0:
            return
        for store in placement:
            if missing <= 0:
                break
            if store in holders:
                continue
            try:
                store.write_chunk(chunk, chunk_id=chunk_id)
                missing -= 1
                log_event(self._log, _logging.INFO, "replica_repaired",
                          chunk_id=chunk_id, location=store.root)
            except OSError:
                continue

    def read_meta(self, chunk_id: str) -> dict:
        for store in self._placement(chunk_id):
            try:
                return store.read_meta(chunk_id)
            except (YtError, OSError):
                continue
        raise YtError(f"No such chunk {chunk_id}",
                      code=EErrorCode.NoSuchChunk)

    def exists(self, chunk_id: str) -> bool:
        return any(store.exists(chunk_id) for store in self.locations)

    def remove_chunk(self, chunk_id: str) -> None:
        for store in self.locations:
            store.remove_chunk(chunk_id)

    def list_chunks(self) -> list[str]:
        out: set[str] = set()
        for store in self.locations:
            out.update(store.list_chunks())
        return sorted(out)
