"""Master cache: a read-through caching proxy for master metadata reads.

Ref: the master_cache role (yt/yt/server/master_cache) — hot metadata
reads (get/exists/list) fan IN to a cache process so the master answers
each popular path once per TTL instead of once per client.  The cache
speaks the SAME driver wire surface as the primary's DriverService, so
any thin client points at it unchanged; mutations and uncacheable
commands forward verbatim.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ytsaurus_tpu import yson
from ytsaurus_tpu.rpc import Channel, RetryingChannel, Service, rpc_method
from ytsaurus_tpu.rpc.wire import wire_text as _text
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("master_cache")

# Pure reads over master metadata: safe to serve ttl-stale.
CACHEABLE_COMMANDS = frozenset({"get", "exists", "list"})


class MasterCacheService(Service):
    name = "driver"                 # same surface as DriverService

    def __init__(self, upstream_address: str, ttl: float = 2.0,
                 max_entries: int = 10_000, timeout: float = 60.0):
        self.upstream_address = upstream_address
        self.ttl = ttl
        self.max_entries = max_entries
        self._channel = RetryingChannel(
            Channel(upstream_address, timeout=timeout), attempts=3,
            backoff=0.2)
        self._cache: dict = {}      # key → (expiry, body, attachments)
        self._lock = threading.Lock()
        self.stats = {"hits": 0, "misses": 0, "forwarded": 0}

    def _key(self, command: str, user: str, parameters: dict) -> bytes:
        return yson.dumps({"c": command, "u": user, "p": parameters},
                          binary=True)

    @rpc_method()
    def ping(self, body, attachments):
        return {"ok": True, "cache": dict(self.stats)}

    # Transactions are primary-side state: every tx verb of the driver
    # surface forwards verbatim so a thin client pointed at the cache
    # keeps its full API (the docstring's contract).
    def _forward(self, method: str, body, attachments):
        # RPC methods dispatch concurrently (execute runs at
        # concurrency=16): the tally must ride the cache lock like the
        # hit/miss counters, or increments are lost under contention.
        with self._lock:
            self.stats["forwarded"] += 1
        return self._channel.call("driver", method, body, attachments,
                                  idempotent=False)

    @rpc_method()
    def start_transaction(self, body, attachments):
        return self._forward("start_transaction", body, attachments)

    @rpc_method()
    def commit_transaction(self, body, attachments):
        return self._forward("commit_transaction", body, attachments)

    @rpc_method()
    def abort_transaction(self, body, attachments):
        return self._forward("abort_transaction", body, attachments)

    @rpc_method()
    def insert_rows_tx(self, body, attachments):
        return self._forward("insert_rows_tx", body, attachments)

    @rpc_method()
    def delete_rows_tx(self, body, attachments):
        return self._forward("delete_rows_tx", body, attachments)

    @rpc_method(concurrency=16)
    def execute(self, body, attachments):
        command = _text(body["command"])
        parameters = body.get("parameters") or {}
        user = _text(body.get("user") or "root")
        if command not in CACHEABLE_COMMANDS or attachments:
            with self._lock:
                self.stats["forwarded"] += 1
            return self._channel.call(
                "driver", "execute", body, attachments,
                idempotent=not _is_mutating(command))
        key = self._key(command, user, parameters)
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None and hit[0] > now:
                self.stats["hits"] += 1
                return hit[1], list(hit[2])
        out_body, out_attachments = self._channel.call(
            "driver", "execute", body, ())
        with self._lock:
            self.stats["misses"] += 1
            if len(self._cache) >= self.max_entries:
                # Cheap pressure valve: drop expired entries, then the
                # oldest-expiring half if still over.
                self._cache = {k: v for k, v in self._cache.items()
                               if v[0] > now}
                if len(self._cache) >= self.max_entries:
                    by_expiry = sorted(self._cache.items(),
                                       key=lambda kv: kv[1][0])
                    self._cache = dict(by_expiry[len(by_expiry) // 2:])
            self._cache[key] = (now + self.ttl, out_body,
                                list(out_attachments))
        return out_body, list(out_attachments)


def _is_mutating(command: str) -> bool:
    from ytsaurus_tpu.driver import COMMANDS
    descriptor = COMMANDS.get(command)
    return bool(descriptor and descriptor.is_mutating)


# analyze: allow(failpoint): daemon entry point — bootstrap plumbing; cache-miss faults inject at rpc.channel sites
def run_master_cache(root: str, port: int, primary_address: str,
                     ttl: float = 2.0) -> None:
    """Daemon entry (--role master_cache)."""
    import os

    from ytsaurus_tpu.rpc import RpcServer
    os.makedirs(root, exist_ok=True)
    service = MasterCacheService(primary_address, ttl=ttl)
    server = RpcServer([service], port=port)
    server.start()
    path = os.path.join(root, "master_cache.port")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(server.port))
    os.replace(tmp, path)
    print(f"master cache serving on {server.address} -> "
          f"{primary_address} (ttl {ttl}s)", flush=True)
    threading.Event().wait()
