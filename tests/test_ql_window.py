"""Window-function corpus: ranking / offset / framed aggregates over the
segmented-prefix-scan subsystem (query/engine/window.py), each family
dual-checked local vs 8-device SPMD like test_ql_corpus2.py.

Coverage per ISSUE 1: NULLs (in arguments AND partition keys), ties,
empty partitions (filtered away), single-row partitions, explicit ROWS
frames, the CH/ANSI dialect spelling, and both distributed executions
(PARTITION-BY co-partition shuffle and the gather-merge fallback).
"""

import pytest

from tests.harness import evaluate
from ytsaurus_tpu.errors import YtError

T = "//t"

W_COLS = [("k", "int64", "ascending"), ("g", "string"), ("t", "int64"),
          ("v", "int64"), ("x", "double")]

# Partition "a": 4 rows (tie on t=20, one null v); "b": 2 rows (tied v);
# NULL partition: 2 rows; "c": single row with null v.
W_ROWS = [
    (1, "a", 10, 5, 1.5),
    (2, "a", 20, 3, -0.5),
    (3, "a", 20, None, 2.0),
    (4, "a", 40, 7, None),
    (5, "b", 10, 2, 4.0),
    (6, "b", 30, 2, 1.0),
    (7, None, 10, 9, 0.0),
    (8, None, 20, 1, None),
    (9, "c", 10, None, 3.0),
]

WT = {T: (W_COLS, W_ROWS)}


def rows(col, values):
    return [{"k": k, col: v} for k, v in zip(range(1, 10), values)]


def run(query, expected, tables=None, ordered=False):
    evaluate(query, tables or WT, expected, ordered=ordered)


# ---------------------------------------------------------------------------
# A. ranking: row_number / rank / dense_rank
# ---------------------------------------------------------------------------

RANKING = [
    ("row_number_by_t",
     f"k, row_number() OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [1, 2, 3, 4, 1, 2, 1, 2, 1])),
    ("rank_ties_share",
     f"k, rank() OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [1, 2, 2, 4, 1, 2, 1, 2, 1])),
    ("dense_rank_no_gaps",
     f"k, dense_rank() OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [1, 2, 2, 3, 1, 2, 1, 2, 1])),
    ("rank_desc_nulls_last",
     f"k, rank() OVER (PARTITION BY g ORDER BY v DESC) AS r FROM [{T}]",
     rows("r", [2, 3, 4, 1, 1, 1, 1, 2, 1])),
    ("dense_rank_tied_values",
     f"k, dense_rank() OVER (PARTITION BY g ORDER BY v) AS r FROM [{T}]",
     rows("r", [3, 2, 1, 4, 1, 1, 2, 1, 1])),
    ("row_number_global",
     f"k, row_number() OVER (ORDER BY k) AS r FROM [{T}]",
     rows("r", [1, 2, 3, 4, 5, 6, 7, 8, 9])),
    ("row_number_no_order",
     f"k, row_number() OVER (PARTITION BY g) AS r FROM [{T}]",
     rows("r", [1, 2, 3, 4, 1, 2, 1, 2, 1])),
    ("rank_two_order_keys",
     f"k, rank() OVER (PARTITION BY g ORDER BY t, v DESC) AS r "
     f"FROM [{T}]",
     rows("r", [1, 2, 3, 4, 1, 2, 1, 2, 1])),
    ("rank_filtered_partition_gone",
     f"k, rank() OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}] "
     "WHERE v > 1",
     [{"k": 1, "r": 1}, {"k": 2, "r": 2}, {"k": 4, "r": 3},
      {"k": 5, "r": 1}, {"k": 6, "r": 2}, {"k": 7, "r": 1}]),
    ("row_number_single_row_partition",
     f"k, row_number() OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}] "
     "WHERE g = 'c'", [{"k": 9, "r": 1}]),
    ("rank_in_expression",
     f"k, rank() OVER (PARTITION BY g ORDER BY t) * 10 AS r FROM [{T}] "
     "WHERE g = 'b'", [{"k": 5, "r": 10}, {"k": 6, "r": 20}]),
    ("row_number_empty_result",
     f"k, row_number() OVER (ORDER BY k) AS r FROM [{T}] WHERE v > 100",
     []),
]


@pytest.mark.parametrize("query,expected", [c[1:] for c in RANKING],
                         ids=[c[0] for c in RANKING])
def test_ranking_family(query, expected):
    run(query, expected)


# ---------------------------------------------------------------------------
# B. offset functions: lag / lead / first_value / last_value
# ---------------------------------------------------------------------------

OFFSET = [
    ("lag_basic",
     f"k, lag(v) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [None, 5, 3, None, None, 2, None, 9, None])),
    ("lag_two",
     f"k, lag(v, 2) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [None, None, 5, 3, None, None, None, None, None])),
    ("lag_default_at_edge",
     f"k, lag(v, 1, -1) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [-1, 5, 3, None, -1, 2, -1, 9, -1])),
    ("lag_zero_is_self",
     f"k, lag(v, 0) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [5, 3, None, 7, 2, 2, 9, 1, None])),
    ("lead_basic",
     f"k, lead(v) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [3, None, 7, None, 2, None, 1, None, None])),
    ("lead_default",
     f"k, lead(v, 1, 0) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [3, None, 7, 0, 2, 0, 1, 0, 0])),
    ("lead_overshoot_whole_partition",
     f"k, lead(v, 9, -7) OVER (PARTITION BY g ORDER BY t) AS r "
     f"FROM [{T}]", rows("r", [-7] * 9)),
    ("lag_double_column",
     f"k, lag(x) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [None, 1.5, -0.5, 2.0, None, 4.0, None, 0.0, None])),
    ("lag_string_column",
     f"k, lag(g) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [None, b"a", b"a", b"a", None, b"b", None, None, None])),
    ("first_value_running",
     f"k, first_value(v) OVER (PARTITION BY g ORDER BY t) AS r "
     f"FROM [{T}]", rows("r", [5, 5, 5, 5, 2, 2, 9, 9, None])),
    ("last_value_default_frame_is_peer_end",
     # Standard default frame: last_value reaches the END of the current
     # peer group (the current row itself when order keys are unique).
     f"k, last_value(v) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]",
     rows("r", [5, None, None, 7, 2, 2, 9, 1, None])),
    ("last_value_unique_keys_is_current_row",
     f"k, last_value(v) OVER (PARTITION BY g ORDER BY t, k) AS r "
     f"FROM [{T}]", rows("r", [5, 3, None, 7, 2, 2, 9, 1, None])),
    ("last_value_unbounded_frame",
     f"k, last_value(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN "
     f"UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) AS r FROM [{T}]",
     rows("r", [7, 7, 7, 7, 2, 2, 1, 1, None])),
    ("first_value_whole_partition_no_order",
     f"k, first_value(v) OVER (PARTITION BY g) AS r FROM [{T}]",
     rows("r", [5, 5, 5, 5, 2, 2, 9, 9, None])),
    ("lag_expression_argument",
     f"k, lag(v * 2) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}] "
     "WHERE g = 'a'",
     [{"k": 1, "r": None}, {"k": 2, "r": 10}, {"k": 3, "r": 6},
      {"k": 4, "r": None}]),
]


@pytest.mark.parametrize("query,expected", [c[1:] for c in OFFSET],
                         ids=[c[0] for c in OFFSET])
def test_offset_family(query, expected):
    run(query, expected)


# ---------------------------------------------------------------------------
# C. framed aggregates: sum / min / max / avg / count over ROWS frames
# ---------------------------------------------------------------------------

FRAMED = [
    ("running_sum_acceptance",
     f"k, sum(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN UNBOUNDED "
     f"PRECEDING AND CURRENT ROW) AS s FROM [{T}]",
     rows("s", [5, 8, 8, 15, 2, 4, 9, 10, None])),
    ("running_sum_implicit_frame",
     f"k, sum(v) OVER (PARTITION BY g ORDER BY t) AS s FROM [{T}]",
     rows("s", [5, 8, 8, 15, 2, 4, 9, 10, None])),
    ("implicit_frame_is_peer_extent",
     # The SQL-standard default (RANGE UNBOUNDED PRECEDING..CURRENT
     # ROW): tied order keys share one running sum — 30, 30, 60, never
     # the tie-order-dependent 10, 30, 60 a ROWS default would give.
     f"k, sum(v) OVER (ORDER BY t) AS s FROM [{T}]",
     [{"k": 1, "s": 30}, {"k": 2, "s": 30}, {"k": 3, "s": 60}],
     {T: ([("k", "int64", "ascending"), ("t", "int64"), ("v", "int64")],
          [(1, 1, 10), (2, 1, 20), (3, 2, 30)])}),
    ("whole_partition_sum",
     f"k, sum(v) OVER (PARTITION BY g) AS s FROM [{T}]",
     rows("s", [15, 15, 15, 15, 4, 4, 10, 10, None])),
    ("global_sum_no_partition",
     f"k, sum(v) OVER () AS s FROM [{T}]", rows("s", [29] * 9)),
    ("running_count",
     f"k, count(v) OVER (PARTITION BY g ORDER BY t) AS c FROM [{T}]",
     rows("c", [1, 2, 2, 3, 1, 2, 1, 2, 0])),
    ("count_star_rows_peer_extent",
     # Implicit default frame = RANGE-peers: the tied rows (k2, k3 at
     # t=20) share one count.
     f"k, count(*) OVER (PARTITION BY g ORDER BY t) AS c FROM [{T}]",
     rows("c", [1, 3, 3, 4, 1, 2, 1, 2, 1])),
    ("count_star_explicit_rows_frame",
     f"k, count(*) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN "
     f"UNBOUNDED PRECEDING AND CURRENT ROW) AS c FROM [{T}]",
     rows("c", [1, 2, 3, 4, 1, 2, 1, 2, 1])),
    ("running_avg",
     f"k, avg(v) OVER (PARTITION BY g ORDER BY t) AS a FROM [{T}] "
     "WHERE g = 'a'",
     [{"k": 1, "a": 5.0}, {"k": 2, "a": 4.0}, {"k": 3, "a": 4.0},
      {"k": 4, "a": 5.0}]),
    ("whole_partition_avg",
     f"k, avg(v) OVER (PARTITION BY g) AS a FROM [{T}]",
     rows("a", [5.0, 5.0, 5.0, 5.0, 2.0, 2.0, 5.0, 5.0, None])),
    ("sum_one_preceding",
     f"k, sum(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN 1 "
     f"PRECEDING AND CURRENT ROW) AS s FROM [{T}]",
     rows("s", [5, 8, 3, 7, 2, 4, 9, 10, None])),
    ("sum_preceding_and_following",
     f"k, sum(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN 1 "
     f"PRECEDING AND 1 FOLLOWING) AS s FROM [{T}]",
     rows("s", [8, 8, 10, 7, 4, 4, 10, 10, None])),
    ("sum_suffix_frame",
     f"k, sum(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN CURRENT "
     f"ROW AND UNBOUNDED FOLLOWING) AS s FROM [{T}]",
     rows("s", [15, 10, 7, 7, 4, 2, 10, 1, None])),
    ("sum_strictly_preceding",
     f"k, sum(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN 2 "
     f"PRECEDING AND 1 PRECEDING) AS s FROM [{T}]",
     rows("s", [None, 5, 8, 3, None, 2, None, 9, None])),
    ("count_empty_frame_is_zero",
     f"k, count(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN 2 "
     f"FOLLOWING AND 5 FOLLOWING) AS c FROM [{T}]",
     rows("c", [1, 1, 0, 0, 0, 0, 0, 0, 0])),   # k3's v is null
    ("sum_empty_frame_is_null",
     f"k, sum(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN 3 "
     f"FOLLOWING AND 5 FOLLOWING) AS s FROM [{T}]",
     rows("s", [7, None, None, None, None, None, None, None, None])),
    ("running_min",
     f"k, min(v) OVER (PARTITION BY g ORDER BY t) AS m FROM [{T}]",
     rows("m", [5, 3, 3, 3, 2, 2, 9, 1, None])),
    ("running_max",
     f"k, max(v) OVER (PARTITION BY g ORDER BY t) AS m FROM [{T}]",
     rows("m", [5, 5, 5, 7, 2, 2, 9, 9, None])),
    ("min_bounded_window",
     f"k, min(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN 1 "
     f"PRECEDING AND 1 FOLLOWING) AS m FROM [{T}]",
     rows("m", [3, 3, 3, 7, 2, 2, 1, 1, None])),
    ("max_bounded_window",
     f"k, max(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN 2 "
     f"PRECEDING AND CURRENT ROW) AS m FROM [{T}]",
     rows("m", [5, 5, 5, 7, 2, 2, 9, 9, None])),
    ("max_suffix_window",
     f"k, max(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN CURRENT "
     f"ROW AND UNBOUNDED FOLLOWING) AS m FROM [{T}]",
     rows("m", [7, 7, 7, 7, 2, 2, 9, 1, None])),
    ("min_double_with_nulls",
     f"k, min(x) OVER (PARTITION BY g ORDER BY t) AS m FROM [{T}]",
     rows("m", [1.5, -0.5, -0.5, -0.5, 4.0, 1.0, 0.0, 0.0, 3.0])),
    ("sum_double",
     f"k, sum(x) OVER (PARTITION BY g ORDER BY t, k) AS s FROM [{T}] "
     "WHERE g = 'a'",
     [{"k": 1, "s": 1.5}, {"k": 2, "s": 1.0}, {"k": 3, "s": 3.0},
      {"k": 4, "s": 3.0}]),
    ("mixed_items_one_query",
     f"k, sum(v) OVER (PARTITION BY g ORDER BY t) AS s, "
     f"rank() OVER (PARTITION BY g ORDER BY t) AS r, "
     f"count(v) OVER (PARTITION BY g) AS c FROM [{T}] WHERE g = 'b'",
     [{"k": 5, "s": 2, "r": 1, "c": 2},
      {"k": 6, "s": 4, "r": 2, "c": 2}]),
    ("window_then_top_level_order",
     f"k, sum(v) OVER (PARTITION BY g ORDER BY t) AS s FROM [{T}] "
     "WHERE g = 'a' "
     "ORDER BY sum(v) OVER (PARTITION BY g ORDER BY t) DESC, k ASC "
     "LIMIT 3",
     [{"k": 4, "s": 15}, {"k": 2, "s": 8}, {"k": 3, "s": 8}]),
]


@pytest.mark.parametrize("query,expected,tables",
                         [(c[1], c[2], c[3] if len(c) > 3 else None)
                          for c in FRAMED],
                         ids=[c[0] for c in FRAMED])
def test_framed_aggregate_family(query, expected, tables):
    ordered = "LIMIT" in query
    run(query, expected, tables=tables, ordered=ordered)


# ---------------------------------------------------------------------------
# D. CH/ANSI dialect spelling (ecosystem/sql.py)
# ---------------------------------------------------------------------------

SQL_DIALECT = [
    ("sql_running_sum",
     'SELECT k, sum(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN '
     'UNBOUNDED PRECEDING AND CURRENT ROW) AS s FROM "//t"',
     rows("s", [5, 8, 8, 15, 2, 4, 9, 10, None])),
    ("sql_row_number",
     'SELECT k, row_number() OVER (PARTITION BY g ORDER BY t DESC) '
     'AS r FROM `//t`',
     rows("r", [4, 2, 3, 1, 2, 1, 2, 1, 1])),
    ("sql_lag_lead",
     'SELECT k, lag(v, 1, 0) OVER (PARTITION BY g ORDER BY t) AS l '
     'FROM "//t" WHERE g == \'b\'',
     [{"k": 5, "l": 0}, {"k": 6, "l": 2}]),
]


@pytest.mark.parametrize("sql,expected", [c[1:] for c in SQL_DIALECT],
                         ids=[c[0] for c in SQL_DIALECT])
def test_sql_dialect_windows(sql, expected):
    from ytsaurus_tpu.ecosystem.sql import translate_sql
    run(translate_sql(sql), expected)


# ---------------------------------------------------------------------------
# E. validation errors
# ---------------------------------------------------------------------------

ERRORS = [
    ("rank_requires_order",
     f"k, rank() OVER (PARTITION BY g) AS r FROM [{T}]"),
    ("frame_requires_order",
     f"k, sum(v) OVER (PARTITION BY g ROWS BETWEEN 1 PRECEDING AND "
     f"CURRENT ROW) AS s FROM [{T}]"),
    ("frame_on_ranking_function",
     f"k, rank() OVER (PARTITION BY g ORDER BY t ROWS BETWEEN 1 "
     f"PRECEDING AND CURRENT ROW) AS r FROM [{T}]"),
    ("window_in_where",
     f"k FROM [{T}] WHERE rank() OVER (PARTITION BY g ORDER BY t) = 1"),
    ("window_with_group_by",
     f"g, sum(rank() OVER (ORDER BY t)) AS s FROM [{T}] GROUP BY g"),
    ("mismatched_partition_specs",
     f"k, rank() OVER (PARTITION BY g ORDER BY t) AS a, "
     f"rank() OVER (PARTITION BY v ORDER BY t) AS b FROM [{T}]"),
    ("mismatched_order_specs",
     f"k, rank() OVER (PARTITION BY g ORDER BY t) AS a, "
     f"rank() OVER (PARTITION BY g ORDER BY v) AS b FROM [{T}]"),
    ("lag_negative_offset",
     f"k, lag(v, -1) OVER (PARTITION BY g ORDER BY t) AS r FROM [{T}]"),
    ("frame_start_after_end",
     f"k, sum(v) OVER (PARTITION BY g ORDER BY t ROWS BETWEEN 1 "
     f"FOLLOWING AND 1 PRECEDING) AS s FROM [{T}]"),
    ("sum_over_string",
     f"k, sum(g) OVER (PARTITION BY v ORDER BY t) AS s FROM [{T}]"),
    ("unknown_window_function",
     f"k, ntile(4) OVER (ORDER BY t) AS r FROM [{T}]"),
]


@pytest.mark.parametrize("query", [c[1] for c in ERRORS],
                         ids=[c[0] for c in ERRORS])
def test_window_errors(query):
    with pytest.raises(YtError):
        evaluate(query, WT)


# ---------------------------------------------------------------------------
# F. SPMD dual-check: local vs 8-device mesh, both distributed paths
#    (PARTITION-BY co-partition shuffle AND the gather-merge fallback)
# ---------------------------------------------------------------------------

SPMD_SCHEMA = [("k", "int64", "ascending"), ("g", "string"),
               ("t", "int64"), ("v", "int64"), ("x", "double")]


def _spmd_fixture():
    import numpy as np

    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.schema import TableSchema

    rng = np.random.default_rng(11)
    parts = np.array([b"p0", b"p1", b"p2", b"p3", b"p4", b""],
                     dtype=object)
    schema = TableSchema.make(SPMD_SCHEMA)
    chunks = []
    base = 0
    for shard in range(8):
        n = 35 + shard * 6
        rows_ = []
        for i in range(n):
            rows_.append((
                base + i,
                None if i % 13 == 0 else parts[int(rng.integers(0, 6))],
                int(rng.integers(0, 40)),          # many cross-shard ties
                None if i % 7 == 0 else int(rng.integers(-20, 20)),
                float(rng.uniform(-3, 3))))
        base += n
        chunks.append(ColumnarChunk.from_rows(schema, rows_))
    return make_mesh(8), schema, chunks


@pytest.fixture(scope="module")
def spmd_env():
    return _spmd_fixture()


# Unique ORDER BY tiebreak (k) wherever intra-tie order changes results
# (row_number/lag/running sums); rank/dense_rank keep deliberate ties.
# Items are CONSOLIDATED per query (one sort serves every item), so each
# family rides one 8-device compile instead of one per function.
SPMD_WINDOW_SQL = {
    "ranking_running_spmd":
        f"k, sum(v) OVER (PARTITION BY g ORDER BY t, k ROWS BETWEEN "
        f"UNBOUNDED PRECEDING AND CURRENT ROW) AS s, "
        f"row_number() OVER (PARTITION BY g ORDER BY t, k) AS n, "
        f"count(v) OVER (PARTITION BY g ORDER BY t, k) AS c FROM [{T}]",
    "rank_cross_shard_ties_spmd":
        f"k, rank() OVER (PARTITION BY g ORDER BY t) AS r, "
        f"dense_rank() OVER (PARTITION BY g ORDER BY t) AS d FROM [{T}]",
    "offset_first_last_spmd":
        f"k, lag(v, 1, -99) OVER (PARTITION BY g ORDER BY t, k) AS l, "
        f"lead(v) OVER (PARTITION BY g ORDER BY t, k) AS e, "
        f"first_value(v) OVER (PARTITION BY g ORDER BY t, k) AS f, "
        f"last_value(v) OVER (PARTITION BY g ORDER BY t, k ROWS BETWEEN "
        f"UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) AS z FROM [{T}]",
    "bounded_frame_spmd":
        f"k, sum(v) OVER (PARTITION BY g ORDER BY t, k ROWS BETWEEN 2 "
        f"PRECEDING AND 1 FOLLOWING) AS s, min(v) OVER (PARTITION BY g "
        f"ORDER BY t, k ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) AS m, "
        f"max(x) OVER (PARTITION BY g ORDER BY t, k) AS h FROM [{T}]",
    "filtered_whole_partition_spmd":
        f"k, count(v) OVER (PARTITION BY g ORDER BY t, k) AS c, "
        f"avg(v) OVER (PARTITION BY g) AS a "
        f"FROM [{T}] WHERE v != 0 AND t < 30",
    "windowed_then_order_limit_spmd":
        f"k, sum(v) OVER (PARTITION BY g ORDER BY t, k) AS s FROM [{T}] "
        f"ORDER BY sum(v) OVER (PARTITION BY g ORDER BY t, k) DESC, "
        f"k ASC LIMIT 11",
}


# Every family dual-checks local vs SPMD on the default co-partition
# path; one representative query also exercises the gather-merge
# fallback (compiling every query under BOTH modes would double the
# 8-device jit time for no added coverage).
_GATHER_CASES = {"ranking_running_spmd"}


@pytest.mark.parametrize("case,shuffle",
                         [(c, None) for c in sorted(SPMD_WINDOW_SQL)]
                         + [(c, False) for c in sorted(_GATHER_CASES)],
                         ids=[f"{c}-copartition"
                              for c in sorted(SPMD_WINDOW_SQL)]
                         + [f"{c}-gather" for c in sorted(_GATHER_CASES)])
def test_spmd_window_matches_local(case, shuffle, spmd_env):
    """Every window family answers IDENTICALLY on the local single-chunk
    path and the 8-shard SPMD path — through the PARTITION-BY-hash
    co-partition shuffle (default) AND the gather-merge fallback."""
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        ShardedTable,
    )
    from ytsaurus_tpu.query.builder import build_query

    mesh, schema, chunks = spmd_env
    query = SPMD_WINDOW_SQL[case]
    local = evaluate(query, {T: concat_chunks(chunks)})
    plan = build_query(query, {T: schema})
    table = ShardedTable.from_chunks(mesh, chunks)
    spmd = DistributedEvaluator(mesh).run(plan, table,
                                          shuffle=shuffle).to_rows()
    if "LIMIT" in query:
        # Deterministic top-level order (unique tiebreak): the SEQUENCE
        # is the contract.
        assert spmd == local, f"SPMD order diverged for: {query}"
        return
    # ORDERED comparison, not set comparison: rows keyed by the unique
    # k, then full-row sequence equality (multiplicity and every column
    # value must match exactly).
    assert sorted(spmd, key=lambda r: r["k"]) == \
        sorted(local, key=lambda r: r["k"]), \
        f"SPMD diverged from local for: {query}"


def test_spmd_window_host_coordinator(spmd_env):
    """The host-coordinated fan-out (query/coordinator.py split) also
    computes exact windows: the bottom only filters, the front runs the
    window stage over the merged rowset."""
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.coordinator import coordinate_and_execute

    _, schema, chunks = spmd_env
    query = (f"k, sum(v) OVER (PARTITION BY g ORDER BY t, k) AS s, "
             f"rank() OVER (PARTITION BY g ORDER BY t, k) AS r "
             f"FROM [{T}] WHERE t != 7 LIMIT 2000")
    local = evaluate(query, {T: concat_chunks(chunks)})
    plan = build_query(query, {T: schema})
    result = coordinate_and_execute(plan, list(chunks)).to_rows()
    assert sorted(result, key=lambda r: r["k"]) == \
        sorted(local, key=lambda r: r["k"])
