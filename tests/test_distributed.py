"""SPMD (shard_map) distributed execution tests on the virtual 8-device mesh."""

import numpy as np
import pytest

# Minutes of 8-device shard_map compiles: excluded from the tier-1 quick
# pass (-m 'not slow'); the SPMD paths stay tier-1-covered by the
# dual-check families in test_ql_corpus2.py / test_ql_window.py.
pytestmark = pytest.mark.slow

from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.parallel.distributed import DistributedEvaluator, ShardedTable
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.schema import TableSchema

SCHEMA = TableSchema.make([
    ("k", "int64", "ascending"), ("g", "int64"), ("v", "double")])
T = "//t"


@pytest.fixture(scope="module")
def table8():
    from ytsaurus_tpu.parallel.mesh import make_mesh
    rng = np.random.default_rng(42)
    chunks = []
    for s in range(8):
        n = 100 + s * 13
        chunks.append(ColumnarChunk.from_arrays(
            SCHEMA,
            {"k": np.arange(n) + s * 10_000,
             "g": rng.integers(0, 5, n),
             "v": rng.uniform(0, 10, n)}))
    return make_mesh(8), chunks


def _numpy_rows(chunks):
    rows = []
    for c in chunks:
        rows.extend(c.to_rows())
    return rows


def test_spmd_group_by_matches_host(table8):
    mesh, chunks = table8
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(
        f"g, sum(v) AS s, count(*) AS c, avg(v) AS a FROM [{T}] GROUP BY g",
        {T: SCHEMA})
    out = ev.run(plan, table).to_rows()
    # numpy oracle
    rows = _numpy_rows(chunks)
    want = {}
    for r in rows:
        e = want.setdefault(r["g"], [0.0, 0])
        e[0] += r["v"]
        e[1] += 1
    assert len(out) == len(want)
    for r in sorted(out, key=lambda r: r["g"]):
        s, c = want[r["g"]]
        assert abs(r["s"] - s) < 1e-6
        assert r["c"] == c
        assert abs(r["a"] - s / c) < 1e-9


def test_spmd_filter_scan(table8):
    mesh, chunks = table8
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(f"k FROM [{T}] WHERE v > 9.0", {T: SCHEMA})
    out = ev.run(plan, table).to_rows()
    want = sorted(r["k"] for r in _numpy_rows(chunks) if r["v"] > 9.0)
    assert sorted(r["k"] for r in out) == want


def test_spmd_top_k(table8):
    mesh, chunks = table8
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(f"k, v FROM [{T}] ORDER BY v DESC LIMIT 5", {T: SCHEMA})
    out = ev.run(plan, table).to_rows()
    want = sorted(_numpy_rows(chunks), key=lambda r: -r["v"])[:5]
    assert [r["k"] for r in out] == [r["k"] for r in want]


def test_spmd_string_group_keys():
    import jax
    from ytsaurus_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(8)
    schema = TableSchema.make([("k", "int64", "ascending"), ("s", "string")])
    names = ["ant", "bee", "cat", "dog"]
    chunks = []
    for d in range(8):
        rows = [(d * 100 + i, names[(d + i) % 4]) for i in range(10)]
        chunks.append(ColumnarChunk.from_rows(schema, rows))
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(f"s, count(*) AS c FROM [{T}] GROUP BY s", {T: schema})
    out = ev.run(plan, table).to_rows()
    assert sorted((r["s"], r["c"]) for r in out) == \
        [(b"ant", 20), (b"bee", 20), (b"cat", 20), (b"dog", 20)]


def test_spmd_shuffled_group_by_matches_gather():
    # High-cardinality GROUP BY via all_to_all repartition: results must
    # match the gather-merge path and the numpy oracle exactly.
    from ytsaurus_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(8)
    rng = np.random.default_rng(5)
    schema = TableSchema.make([("k", "int64", "ascending"), ("g", "int64"),
                               ("v", "double")])
    chunks = []
    for s in range(8):
        n = 400
        chunks.append(ColumnarChunk.from_arrays(
            schema, {"k": np.arange(n) + s * n,
                     "g": rng.integers(0, 500, n),      # ~500 groups
                     "v": rng.uniform(0, 1, n)}))
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(
        "g, sum(v) AS s, count(*) AS c FROM [//t] GROUP BY g "
        "ORDER BY g LIMIT 1000", {T: schema})
    shuffled = ev.run(plan, table, shuffle=True).to_rows()
    gathered = ev.run(plan, table, shuffle=False).to_rows()
    # Sums accumulate in different orders across the two paths → compare
    # with a float tolerance, exact for keys/counts.
    assert [r["g"] for r in shuffled] == [r["g"] for r in gathered]
    assert [r["c"] for r in shuffled] == [r["c"] for r in gathered]
    assert all(abs(a["s"] - b["s"]) < 1e-9
               for a, b in zip(shuffled, gathered))
    # numpy oracle
    want = {}
    for c in chunks:
        for r in c.to_rows():
            e = want.setdefault(r["g"], [0.0, 0])
            e[0] += r["v"]
            e[1] += 1
    assert len(shuffled) == len(want)
    for r in shuffled:
        s, cnt = want[r["g"]]
        assert abs(r["s"] - s) < 1e-9 and r["c"] == cnt


def test_spmd_shuffled_having_and_strings():
    from ytsaurus_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(8)
    schema = TableSchema.make([("k", "int64", "ascending"), ("s", "string"),
                               ("v", "int64")])
    words = [f"w{i:03d}" for i in range(60)]
    chunks = []
    for d in range(8):
        rows = [(d * 100 + i, words[(d * 13 + i) % 60], i) for i in range(50)]
        chunks.append(ColumnarChunk.from_rows(schema, rows))
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    plan = build_query(
        "s, sum(v) AS t FROM [//t] GROUP BY s HAVING sum(v) > 150 "
        "ORDER BY s LIMIT 100", {T: schema})
    shuffled = ev.run(plan, table, shuffle=True).to_rows()
    gathered = ev.run(plan, table, shuffle=False).to_rows()
    assert shuffled == gathered and len(shuffled) > 0


def test_spmd_join_group_matches_host_q3_shape():
    """Device-resident broadcast join (TPC-H Q3 shape): sharded fact table
    joined to a replicated unique-key dimension, then GROUP BY — whole
    pipeline in ONE shard_map program."""
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.query.engine.evaluator import Evaluator

    rng = np.random.default_rng(9)
    lineitem_schema = TableSchema.make([
        ("l_orderkey", "int64"), ("l_extendedprice", "double")])
    orders_schema = TableSchema.make([
        ("o_orderkey", "int64", "ascending"), ("o_custkey", "int64")])
    n_orders = 400
    orders = ColumnarChunk.from_arrays(orders_schema, {
        "o_orderkey": np.arange(n_orders) * 3,
        "o_custkey": rng.integers(0, 20, n_orders)})
    mesh = make_mesh(8)
    chunks = []
    for s in range(8):
        n = 150 + 11 * s
        chunks.append(ColumnarChunk.from_arrays(lineitem_schema, {
            "l_orderkey": rng.integers(0, n_orders * 3, n),  # ~1/3 match
            "l_extendedprice": rng.uniform(1, 100, n)}))
    table = ShardedTable.from_chunks(mesh, chunks)

    query = ("o_custkey, sum(l_extendedprice) AS rev, count(*) AS c "
             "FROM [//li] JOIN [//ord] ON l_orderkey = o_orderkey "
             "GROUP BY o_custkey")
    plan = build_query(query, {"//li": lineitem_schema,
                               "//ord": orders_schema})
    out = DistributedEvaluator(mesh).run(
        plan, table, foreign_chunks={"//ord": orders}).to_rows()

    # Host oracle over the concatenated shards.
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    merged = concat_chunks(chunks)
    want = Evaluator().run_plan(plan, merged,
                                {"//ord": orders}).to_rows()
    got = {r["o_custkey"]: (round(r["rev"], 6), r["c"]) for r in out}
    expect = {r["o_custkey"]: (round(r["rev"], 6), r["c"]) for r in want}
    assert got == expect


def test_spmd_left_join():
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.query.engine.evaluator import Evaluator

    left_schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    dim_schema = TableSchema.make([("dk", "int64", "ascending"),
                                   ("name", "int64")])
    dim = ColumnarChunk.from_arrays(dim_schema, {
        "dk": np.array([0, 2, 4]), "name": np.array([100, 102, 104])})
    mesh = make_mesh(8)
    chunks = [ColumnarChunk.from_arrays(left_schema, {
        "k": np.arange(6) + s, "v": np.full(6, s)}) for s in range(8)]
    table = ShardedTable.from_chunks(mesh, chunks)
    query = ("k, name FROM [//l] LEFT JOIN [//d] ON k = dk")
    plan = build_query(query, {"//l": left_schema, "//d": dim_schema})
    out = DistributedEvaluator(mesh).run(
        plan, table, foreign_chunks={"//d": dim}).to_rows()
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    want = Evaluator().run_plan(plan, concat_chunks(chunks),
                                {"//d": dim}).to_rows()
    canon = lambda rows: sorted((r["k"], r["name"]) for r in rows)
    assert canon(out) == canon(want)


def test_spmd_join_duplicate_foreign_keys_partitioned():
    """Non-unique foreign keys take the partitioned-exchange path (match
    expansion: one output row per (self, foreign) pair)."""
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.query.engine.evaluator import Evaluator

    left_schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    dim_schema = TableSchema.make([("dk", "int64", "ascending"),
                                   ("x", "int64")])
    dim = ColumnarChunk.from_rows(dim_schema.to_unsorted(),
                                  [(1, 10), (1, 11), (2, 20)])
    mesh = make_mesh(8)
    chunks = [ColumnarChunk.from_arrays(left_schema, {
        "k": np.arange(4), "v": np.arange(4)}) for _ in range(8)]
    table = ShardedTable.from_chunks(mesh, chunks)
    plan = build_query("k, x FROM [//l] JOIN [//d] ON k = dk",
                       {"//l": left_schema, "//d": dim_schema})
    out = DistributedEvaluator(mesh).run(
        plan, table, foreign_chunks={"//d": dim}).to_rows()
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    want = Evaluator().run_plan(plan, concat_chunks(chunks),
                                {"//d": dim}).to_rows()
    canon = lambda rows: sorted((r["k"], r["x"]) for r in rows)
    assert canon(out) == canon(want)
    assert len(out) == 8 * (2 + 1)      # k=1 matches twice, k=2 once


def test_spmd_fact_to_fact_join_matches_host():
    """VERDICT r2 #5 done-criterion: a non-unique-key two-fact-table
    join (both sides large, both routed by key hash) matches the host
    oracle on the 8-device mesh, including GROUP BY on top."""
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.query.engine.evaluator import Evaluator

    rng = np.random.default_rng(17)
    a_schema = TableSchema.make([("ak", "int64"), ("av", "double")])
    b_schema = TableSchema.make([("bk", "int64"), ("bv", "int64")])
    n_b = 700
    fact_b = ColumnarChunk.from_arrays(b_schema, {
        "bk": rng.integers(0, 50, n_b),          # heavily duplicated keys
        "bv": rng.integers(0, 1000, n_b)})
    mesh = make_mesh(8)
    chunks = []
    for s in range(8):
        n = 120 + 9 * s
        chunks.append(ColumnarChunk.from_arrays(a_schema, {
            "ak": rng.integers(0, 80, n),        # duplicated, partial overlap
            "av": rng.uniform(0, 10, n)}))
    table = ShardedTable.from_chunks(mesh, chunks)
    query = ("ak, sum(av) AS s, count(*) AS c "
             "FROM [//a] JOIN [//b] ON ak = bk GROUP BY ak")
    plan = build_query(query, {"//a": a_schema, "//b": b_schema})
    ev = DistributedEvaluator(mesh)
    out = ev.run(plan, table, foreign_chunks={"//b": fact_b}).to_rows()
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    want = Evaluator().run_plan(plan, concat_chunks(chunks),
                                {"//b": fact_b}).to_rows()
    got = {r["ak"]: (round(r["s"], 6), r["c"]) for r in out}
    expect = {r["ak"]: (round(r["s"], 6), r["c"]) for r in want}
    assert got == expect
    # Same join under the shuffled GROUP BY path (join + shuffle compose).
    out_sh = ev.run(plan, table, foreign_chunks={"//b": fact_b},
                    shuffle=True).to_rows()
    got_sh = {r["ak"]: (round(r["s"], 6), r["c"]) for r in out_sh}
    assert got_sh == expect


def test_spmd_left_join_duplicates_and_nulls():
    """LEFT join through the partitioned path: null-keyed and unmatched
    self rows survive with null foreign columns; duplicate matches
    expand."""
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.query.engine.evaluator import Evaluator

    left_schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    dim_schema = TableSchema.make([("dk", "int64"), ("x", "int64")])
    dim = ColumnarChunk.from_rows(dim_schema, [(0, 100), (0, 101), (2, 102)])
    mesh = make_mesh(8)
    chunks = [ColumnarChunk.from_rows(left_schema, [
        (0, s), (1, s), (None, s)]) for s in range(8)]
    table = ShardedTable.from_chunks(mesh, chunks)
    plan = build_query("k, v, x FROM [//l] LEFT JOIN [//d] ON k = dk",
                       {"//l": left_schema, "//d": dim_schema})
    out = DistributedEvaluator(mesh).run(
        plan, table, foreign_chunks={"//d": dim}).to_rows()
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    want = Evaluator().run_plan(plan, concat_chunks(chunks),
                                {"//d": dim}).to_rows()
    canon = lambda rows: sorted(
        (r["k"] if r["k"] is not None else -99, r["v"],
         r["x"] if r["x"] is not None else -99) for r in rows)
    assert canon(out) == canon(want)


def test_spmd_string_key_join():
    """String join keys ride merged vocabularies on the SPMD paths (both
    broadcast-unique and partitioned shapes)."""
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.query.engine.evaluator import Evaluator

    left_schema = TableSchema.make([("name", "string"), ("v", "int64")])
    dim_schema = TableSchema.make([("dname", "string"), ("x", "int64")])
    # Unique keys → broadcast path.
    dim_u = ColumnarChunk.from_rows(dim_schema, [
        ("alpha", 1), ("beta", 2), ("gamma", 3)])
    # Duplicate keys → partitioned path.
    dim_d = ColumnarChunk.from_rows(dim_schema, [
        ("alpha", 1), ("alpha", 2), ("delta", 9)])
    mesh = make_mesh(8)
    names = ["alpha", "beta", "delta", "zeta"]
    chunks = [ColumnarChunk.from_rows(left_schema, [
        (names[(s + i) % 4], i) for i in range(5)]) for s in range(8)]
    table = ShardedTable.from_chunks(mesh, chunks)
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    merged = concat_chunks(chunks)
    for dim in (dim_u, dim_d):
        plan = build_query(
            "name, v, x FROM [//l] JOIN [//d] ON name = dname",
            {"//l": left_schema, "//d": dim_schema})
        out = DistributedEvaluator(mesh).run(
            plan, table, foreign_chunks={"//d": dim}).to_rows()
        want = Evaluator().run_plan(plan, merged, {"//d": dim}).to_rows()
        canon = lambda rows: sorted((r["name"], r["v"], r["x"])
                                    for r in rows)
        assert canon(out) == canon(want) and len(out) > 0
