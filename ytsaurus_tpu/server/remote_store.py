"""RpcChunkStore: the FsChunkStore surface over data-node RPC services.

Placement is rendezvous hashing of (chunk_id, node) over the alive-node
list — deterministic, so the primary and any client compute identical
replica sets without a directory lookup (the analog of the master's
chunk_placement.h rack-aware ranking, minus racks).  Reads walk nodes in
rank order and fall back to EVERY node before failing: a shrunken or
reordered alive-list must not lose reachable replicas.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Optional

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.chunks.encoding import (
    DEFAULT_CODEC,
    deserialize_chunk,
    read_chunk_meta,
    serialize_chunk,
)
from ytsaurus_tpu.chunks.store import new_chunk_id
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.rpc import Channel, RetryingChannel
from ytsaurus_tpu.rpc.wire import wire_text as _text
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("chunk_client")


def placement_rank(chunk_id: str, nodes: list[str]) -> list[str]:
    """Deterministic replica ordering shared by all cluster participants."""
    def rank(node: str) -> bytes:
        return hashlib.blake2b((chunk_id + "@" + node).encode(),
                               digest_size=8).digest()
    return sorted(nodes, key=rank)


class RpcChunkStore:
    """Chunk store whose locations are data-node processes."""

    def __init__(self, nodes_provider: Callable[[], list[str]],
                 replication_factor: int = 2, codec: str = DEFAULT_CODEC,
                 timeout: float = 120.0, nodes_ttl: float = 3.0):
        self._nodes_provider = nodes_provider
        self.replication_factor = replication_factor
        self.codec = codec
        self.timeout = timeout
        # Short TTL cache: for remote clients nodes_provider is itself an
        # RPC; per-chunk refresh would double every read's round trips.
        self.nodes_ttl = nodes_ttl
        self._nodes_cache: tuple[float, list[str]] | None = None
        self._channels: dict[str, RetryingChannel] = {}

    def _channel(self, address: str) -> RetryingChannel:
        ch = self._channels.get(address)
        if ch is None:
            ch = RetryingChannel(Channel(address, timeout=self.timeout),
                                 attempts=2, backoff=0.1)
            self._channels[address] = ch
        return ch

    def _nodes(self) -> list[str]:
        import time
        cached = self._nodes_cache
        if cached is not None and time.monotonic() - cached[0] < \
                self.nodes_ttl:
            return cached[1]
        nodes = self._nodes_provider()
        if not nodes:
            raise YtError("No alive data nodes",
                          code=EErrorCode.PeerUnavailable)
        self._nodes_cache = (time.monotonic(), nodes)
        return nodes

    # -- FsChunkStore surface --------------------------------------------------

    def write_chunk(self, chunk: ColumnarChunk,
                    chunk_id: Optional[str] = None,
                    codec: Optional[str] = None,
                    erasure: Optional[str] = None) -> str:
        chunk_id = chunk_id or new_chunk_id()
        blob = serialize_chunk(chunk, codec or self.codec, hunk_store=self)
        self.put_blob(chunk_id, blob, erasure=erasure)
        return chunk_id

    def put_blob(self, chunk_id: str, blob: bytes,
                 erasure: Optional[str] = None) -> str:
        nodes = placement_rank(chunk_id, self._nodes())
        targets = nodes[: self.replication_factor]
        body = {"chunk_id": chunk_id}
        if erasure is not None:
            body["erasure"] = erasure
        written = 0
        errors = []
        for address in targets:
            try:
                self._channel(address).call("data_node", "put_chunk", body,
                                            [blob])
                written += 1
            except YtError as err:
                errors.append(err)
        if written == 0:
            raise YtError(f"Failed to write chunk {chunk_id} to any of "
                          f"{targets}", code=EErrorCode.PeerUnavailable,
                          inner_errors=errors)
        if errors:
            logger.warning("chunk %s under-replicated: %d/%d writes ok",
                           chunk_id, written, len(targets))
        return chunk_id

    def get_blob(self, chunk_id: str) -> bytes:
        nodes = placement_rank(chunk_id, self._nodes())
        errors = []
        # Rank order first (fast path), then every remaining node: replicas
        # written under an older alive-list must stay reachable.
        for address in nodes:
            try:
                _, attachments = self._channel(address).call(
                    "data_node", "get_chunk", {"chunk_id": chunk_id})
                return attachments[0]
            except YtError as err:
                errors.append(err)
                continue
        raise YtError(f"No such chunk {chunk_id} on any node",
                      code=EErrorCode.NoSuchChunk, inner_errors=errors[:3])

    def read_chunk(self, chunk_id: str) -> ColumnarChunk:
        return deserialize_chunk(self.get_blob(chunk_id), hunk_store=self)

    def read_meta(self, chunk_id: str) -> dict:
        return read_chunk_meta(self.get_blob(chunk_id))

    def read_stats(self, chunk_id: str) -> dict:
        """Seal-time column stats from the chunk meta header; pre-stats
        chunks backfill by decoding once (one blob fetch either way)."""
        blob = self.get_blob(chunk_id)
        stats = read_chunk_meta(blob).get("column_stats")
        if stats is None:
            from ytsaurus_tpu.chunks.columnar import chunk_column_stats
            stats = chunk_column_stats(
                deserialize_chunk(blob, hunk_store=self))
        return stats

    def exists(self, chunk_id: str) -> bool:
        for address in placement_rank(chunk_id, self._nodes()):
            try:
                body, _ = self._channel(address).call(
                    "data_node", "has_chunk", {"chunk_id": chunk_id})
                if body.get("exists"):
                    return True
            except YtError:
                continue
        return False

    def remove_chunk(self, chunk_id: str) -> None:
        for address in self._nodes():
            try:
                self._channel(address).call("data_node", "remove_chunk",
                                            {"chunk_id": chunk_id})
            except YtError:
                continue

    def list_chunks(self) -> list[str]:
        out: set[str] = set()
        for address in self._nodes():
            try:
                body, _ = self._channel(address).call(
                    "data_node", "list_chunks", {})
                out.update(_text(c) for c in body.get("chunk_ids", []))
            except YtError:
                continue
        return sorted(out)

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        self._channels.clear()

