"""Skiff + Arrow wire formats.

Ref model: client/formats skiff (schema-driven binary rows) and
client/arrow (IPC stream encoder/decoder over columnar rowsets).
"""

import numpy as np
import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.formats import dumps_skiff, loads_skiff
from ytsaurus_tpu.schema import TableSchema

SCHEMA = TableSchema.make([
    ("k", "int64"), ("u", "uint64"), ("x", "double"),
    ("flag", "boolean"), ("name", "string"),
])

ROWS = [
    {"k": -5, "u": 2 ** 63, "x": 1.5, "flag": True, "name": b"alpha"},
    {"k": 7, "u": 0, "x": -0.25, "flag": False, "name": b"beta"},
    {"k": None, "u": None, "x": None, "flag": None, "name": None},
]


def test_skiff_roundtrip():
    blob = dumps_skiff(ROWS, SCHEMA)
    assert loads_skiff(blob, SCHEMA) == ROWS


def test_skiff_required_dense():
    schema = TableSchema.make([
        {"name": "k", "type": "int64", "required": True},
        {"name": "x", "type": "double", "required": True}])
    blob = dumps_skiff([{"k": 1, "x": 2.0}], schema)
    # Required columns carry no variant tag: row = u16 + 8 + 8 bytes.
    assert len(blob) == 18
    assert loads_skiff(blob, schema) == [{"k": 1, "x": 2.0}]
    with pytest.raises(YtError):
        dumps_skiff([{"k": None, "x": 1.0}], schema)


def test_skiff_truncation_raises(tmp_path):
    blob = dumps_skiff(ROWS, SCHEMA)
    for cut in (1, 3, 9):
        with pytest.raises(YtError):
            loads_skiff(blob[:-cut], SCHEMA)


def test_arrow_empty_table(tmp_path):
    import pyarrow as pa
    client = connect(str(tmp_path))
    client.create("table", "//empty", recursive=True,
                  attributes={"schema": SCHEMA})
    blob = client.read_table("//empty", format="arrow")
    with pa.ipc.open_stream(blob) as reader:
        table = reader.read_all()
    assert table.num_rows == 0
    assert table.column_names == SCHEMA.column_names


def test_skiff_through_client(tmp_path):
    client = connect(str(tmp_path))
    client.write_table("//t", ROWS, schema=SCHEMA)
    blob = client.read_table("//t", format="skiff")
    assert loads_skiff(blob, SCHEMA) == ROWS
    client.write_table("//t2", blob, format="skiff", schema=SCHEMA)
    assert client.read_table("//t2") == ROWS


def test_arrow_roundtrip_through_client(tmp_path):
    import pyarrow as pa
    client = connect(str(tmp_path))
    client.write_table("//t", ROWS, schema=SCHEMA)
    blob = client.read_table("//t", format="arrow")
    with pa.ipc.open_stream(blob) as reader:
        table = reader.read_all()
    assert table.num_rows == 3
    assert table.column("k").to_pylist() == [-5, 7, None]
    assert table.column("name").to_pylist() == [b"alpha", b"beta", None]
    # Strings arrive dictionary-encoded (the columnar planes' layout).
    assert pa.types.is_dictionary(table.schema.field("name").type)
    # Round back into a second table.
    client.write_table("//t2", blob, format="arrow", schema=SCHEMA)
    assert client.read_table("//t2") == ROWS


def test_arrow_write_infers_schema(tmp_path):
    import pyarrow as pa
    client = connect(str(tmp_path))
    table = pa.table({
        "a": pa.array([1, 2, None], type=pa.int64()),
        "s": pa.array(["x", "y", "z"], type=pa.string()),
        "f": pa.array([0.5, None, 2.5], type=pa.float64())})
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    client.write_table("//from_arrow", sink.getvalue().to_pybytes(),
                       format="arrow")
    assert client.read_table("//from_arrow") == [
        {"a": 1, "s": b"x", "f": 0.5},
        {"a": 2, "s": b"y", "f": None},
        {"a": None, "s": b"z", "f": 2.5}]


def test_arrow_zero_copy_numeric_plane():
    from ytsaurus_tpu.arrow import chunk_to_arrow
    chunk = ColumnarChunk.from_arrays(
        TableSchema.make([("v", "int64")]),
        {"v": np.arange(1000, dtype=np.int64)})
    table = chunk_to_arrow(chunk)
    assert table.column("v").to_pylist()[:3] == [0, 1, 2]
    assert table.num_rows == 1000
