"""Discovery server + master cache (aux processes, SURVEY §2.10).

Ref models: yt/yt/server/discovery_server (group membership with TTL
leases) and yt/yt/server/master_cache (read-through metadata cache on
the driver wire surface).
"""

import time

import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from ytsaurus_tpu.client import connect  # noqa: E402
from ytsaurus_tpu.rpc import Channel, RpcServer  # noqa: E402
from ytsaurus_tpu.server.discovery import (  # noqa: E402
    DiscoveryService,
    DiscoveryTracker,
)
from ytsaurus_tpu.server.master_cache import MasterCacheService  # noqa: E402


# -- discovery ---------------------------------------------------------------

def test_discovery_membership_and_ttl():
    tracker = DiscoveryTracker(member_ttl=0.2)
    tracker.heartbeat("/proxies/http", "p1", "h1:80", {"role": "proxy"})
    tracker.heartbeat("/proxies/http", "p2", "h2:80")
    tracker.heartbeat("/trackers", "q1", "h3:81")
    members = tracker.list_members("/proxies/http")
    assert [m["id"] for m in members] == ["p1", "p2"]
    assert members[0]["attributes"] == {"role": "proxy"}
    assert tracker.list_groups() == ["/proxies/http", "/trackers"]
    assert tracker.list_groups("/proxies") == ["/proxies/http"]
    # Lease expiry drops members (and empty groups) without any leave.
    time.sleep(0.25)
    tracker.heartbeat("/proxies/http", "p2", "h2:80")
    assert [m["id"] for m in tracker.list_members("/proxies/http")] == \
        ["p2"]
    assert tracker.list_groups() == ["/proxies/http"]
    # Explicit leave.
    tracker.leave("/proxies/http", "p2")
    assert tracker.list_members("/proxies/http") == []


def test_discovery_over_rpc():
    srv = RpcServer([DiscoveryService(DiscoveryTracker(member_ttl=5.0))])
    srv.start()
    try:
        ch = Channel(srv.address, timeout=15)
        body, _ = ch.call("discovery", "heartbeat",
                          {"group": "/qt", "member_id": "a",
                           "address": "x:1"})
        assert body["ttl"] == 5.0
        body, _ = ch.call("discovery", "list_members", {"group": "/qt"})
        assert [m["address"] for m in body["members"]] == [b"x:1"] or \
            [m["address"] for m in body["members"]] == ["x:1"]
        ch.close()
    finally:
        srv.stop()


def test_discovery_prefix_is_segment_aware():
    tracker = DiscoveryTracker()
    tracker.heartbeat("/proxies/http", "a", "")
    tracker.heartbeat("/proxiesold", "b", "")
    assert tracker.list_groups("/proxies") == ["/proxies/http"]
    assert tracker.list_groups("/proxiesold") == ["/proxiesold"]


def test_discovery_rejects_bad_group():
    tracker = DiscoveryTracker()
    from ytsaurus_tpu.errors import YtError
    with pytest.raises(YtError):
        tracker.heartbeat("no-slash", "m", "")


# -- master cache ------------------------------------------------------------

@pytest.fixture
def upstream(tmp_path):
    from ytsaurus_tpu.server.services import DriverService
    client = connect(str(tmp_path / "m"))
    srv = RpcServer([DriverService(client)])
    srv.start()
    yield client, srv
    srv.stop()


def test_master_cache_serves_stale_within_ttl(upstream, tmp_path):
    client, upstream_srv = upstream
    cache_service = MasterCacheService(upstream_srv.address, ttl=30.0)
    cache_srv = RpcServer([cache_service])
    cache_srv.start()
    try:
        from ytsaurus_tpu.remote_client import connect_remote
        client.create("document", "//cfg/x", recursive=True)
        client.set("//cfg/x", 1)
        through_cache = connect_remote(cache_srv.address)
        assert through_cache.get("//cfg/x") == 1
        assert cache_service.stats["misses"] == 1
        # Repeat: served from cache, upstream not consulted again.
        assert through_cache.get("//cfg/x") == 1
        assert cache_service.stats["hits"] == 1
        # Upstream changes are invisible until the TTL lapses — the
        # documented staleness contract of a metadata cache.
        client.set("//cfg/x", 2)
        assert through_cache.get("//cfg/x") == 1
    finally:
        cache_srv.stop()


def test_master_cache_expires_and_forwards_mutations(upstream):
    client, upstream_srv = upstream
    cache_service = MasterCacheService(upstream_srv.address, ttl=0.2)
    cache_srv = RpcServer([cache_service])
    cache_srv.start()
    try:
        from ytsaurus_tpu.remote_client import connect_remote
        through_cache = connect_remote(cache_srv.address)
        # Mutations forward (and are NOT cached).
        through_cache.create("document", "//d/v", recursive=True)
        through_cache.set("//d/v", 10)
        assert cache_service.stats["forwarded"] >= 2
        assert through_cache.get("//d/v") == 10
        client.set("//d/v", 11)
        time.sleep(0.25)                 # ttl lapse → fresh read
        assert through_cache.get("//d/v") == 11
        # exists/list are cacheable too.
        assert through_cache.exists("//d/v") is True
        assert through_cache.list("//d") == ["v"]
    finally:
        cache_srv.stop()


def test_master_cache_forwards_transactions(upstream, tmp_path):
    """The full driver tx surface works THROUGH the cache (dynamic-table
    writes forward to the primary, which owns the tx state)."""
    from ytsaurus_tpu.schema import TableSchema

    client, upstream_srv = upstream
    cache_srv = RpcServer([MasterCacheService(upstream_srv.address)])
    cache_srv.start()
    try:
        from ytsaurus_tpu.remote_client import connect_remote
        schema = TableSchema.make(
            [("k", "int64", "ascending"), ("v", "int64")],
            unique_keys=True)
        through_cache = connect_remote(cache_srv.address)
        through_cache.create("table", "//dyn/t", recursive=True,
                             attributes={"schema": schema,
                                         "dynamic": True})
        through_cache.mount_table("//dyn/t")
        tx = through_cache.start_transaction()
        through_cache.insert_rows("//dyn/t", [{"k": 1, "v": 10}], tx=tx)
        through_cache.commit_transaction(tx)
        assert through_cache.lookup_rows("//dyn/t", [(1,)]) == [
            {"k": 1, "v": 10}]
    finally:
        cache_srv.stop()