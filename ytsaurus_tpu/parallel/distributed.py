"""SPMD distributed query execution over a device mesh.

The host-coordinated path (query/coordinator.py) loops over shards; this
module is the TPU-native fast path: every shard (tablet analog) lives on its
own device, the bottom query runs as ONE shard_map program, and the front
merge happens on-device via all_gather over ICI — no host round-trip, no bus.

Ref mapping (SURVEY.md §2.8 parallelism table):
  partition-parallel scan  → shard_map over the 'shard' mesh axis
  two-phase aggregation    → per-shard partial states + all_gather + re-group
  (psum applies when group keys are static; the general re-group handles
  arbitrary key sets)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ytsaurus_tpu.chunks.columnar import (
    Column,
    ColumnarChunk,
    unify_dictionaries,
)
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.parallel.mesh import SHARD_AXIS
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.coordinator import split_plan
from ytsaurus_tpu.query.engine.lowering import prepare
from ytsaurus_tpu.schema import EValueType, TableSchema


@dataclass
class _RepColumn:
    """Vocabulary/type carrier used to bind plans without device planes."""
    type: EValueType
    dictionary: Optional[np.ndarray]


@dataclass
class _RepChunk:
    capacity: int
    columns: dict


class ShardedTable:
    """A table partitioned across a device mesh.

    All shards share one schema, one per-shard capacity and ONE unified
    string vocabulary per column (so dictionary codes agree across devices —
    the HBM-staging analog of the reference's in_memory_manager keeping
    chunks resident in a common format, tablet_node/in_memory_manager.h).

    Planes are global arrays of shape (n_shards * capacity,) sharded along
    the mesh axis; each device holds its (capacity,) slice.
    """

    def __init__(self, schema: TableSchema, mesh: Mesh, capacity: int,
                 columns: dict[str, Column], row_counts: list[int],
                 row_valid: jax.Array):
        self.schema = schema
        self.mesh = mesh
        self.capacity = capacity            # per shard
        self.columns = columns              # global sharded planes
        self.row_counts = row_counts
        self.row_valid = row_valid

    @property
    def n_shards(self) -> int:
        return len(self.row_counts)

    @property
    def total_rows(self) -> int:
        return sum(self.row_counts)

    @staticmethod
    def from_chunks(mesh: Mesh, chunks: Sequence[ColumnarChunk]
                    ) -> "ShardedTable":
        n = mesh.devices.size
        if len(chunks) != n:
            raise YtError(f"Need exactly {n} shards for this mesh, "
                          f"got {len(chunks)}",
                          code=EErrorCode.QueryExecutionError)
        schema = chunks[0].schema
        for c in chunks[1:]:
            if c.schema != schema:
                raise YtError("Shard schema mismatch",
                              code=EErrorCode.QueryExecutionError)
        cap = max(c.capacity for c in chunks)
        chunks = [c.with_capacity(cap) for c in chunks]
        shard_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        columns: dict[str, Column] = {}
        for col_schema in schema:
            cols = [c.column(col_schema.name) for c in chunks]
            vocab = None
            if col_schema.type is EValueType.string:
                cols, vocab = unify_dictionaries(cols)
            data = jnp.concatenate([col.data for col in cols])
            valid = jnp.concatenate([col.valid for col in cols])
            data = jax.device_put(data, shard_sharding)
            valid = jax.device_put(valid, shard_sharding)
            columns[col_schema.name] = Column(
                type=col_schema.type, data=data, valid=valid, dictionary=vocab)
        row_valid = jnp.concatenate(
            [jnp.arange(cap) < c.row_count for c in chunks])
        row_valid = jax.device_put(row_valid, shard_sharding)
        return ShardedTable(schema=schema, mesh=mesh, capacity=cap,
                            columns=columns,
                            row_counts=[c.row_count for c in chunks],
                            row_valid=row_valid)

    def rep_chunk(self) -> _RepChunk:
        return _RepChunk(
            capacity=self.capacity,
            columns={name: _RepColumn(type=col.type, dictionary=col.dictionary)
                     for name, col in self.columns.items()})


def _assemble_chunk(prepared_output, out_planes, out_count) -> ColumnarChunk:
    """Materialize prepared-query output planes into a ColumnarChunk."""
    out_columns: dict[str, Column] = {}
    out_schema_cols = []
    for out_col, (data, valid) in zip(prepared_output, out_planes):
        out_schema_cols.append((out_col.name, out_col.type.value))
        out_columns[out_col.name] = Column(
            type=out_col.type, data=data, valid=valid,
            dictionary=out_col.vocab)
    return ColumnarChunk(schema=TableSchema.make(out_schema_cols),
                         row_count=int(out_count), columns=out_columns)


@dataclass
class _JoinSetup:
    """Device-resident broadcast-join plan: replicated sorted foreign
    planes + a traceable per-shard augment step."""
    apply: callable          # (columns, mask, bindings, args) -> (cols, mask)
    bindings: tuple          # host-bound remap/constant slots
    args: tuple              # replicated device planes (P() specs)
    rep_columns: dict        # joined-namespace _RepColumns for prepare()
    fingerprint: tuple


class DistributedEvaluator:
    """Compiles and caches SPMD (join ∘ bottom ∘ all_gather ∘ front)
    programs."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._cache: dict = {}

    def run(self, plan: ir.Query, table: ShardedTable,
            foreign_chunks: Optional[dict] = None,
            shuffle: Optional[bool] = None) -> ColumnarChunk:
        """Execute a plan SPMD.  `shuffle=True` uses the all_to_all
        repartition path for GROUP BY (ref CoordinateAndExecuteWithShuffle,
        engine_api/coordinator.h:92): rows move to hash(key)-owned devices
        and each device computes its COMPLETE groups — right when group
        cardinality is high (the all_gather merge would replicate heavy
        front work).  Default: gather-merge.

        Joined plans run as device-resident broadcast joins: each foreign
        table is key-sorted once, replicated to every device, and probed
        per shard with a vectorized lexicographic binary search (the batch
        reshaping of MultiJoinOpHelper's foreign lookups,
        cg_routines/registry.cpp:599).  Requires unique foreign join keys
        (lookup-join shape, e.g. TPC-H Q3) — others raise QueryUnsupported
        and take the host-coordinated path."""
        join_setup = None
        if plan.joins:
            if shuffle:
                raise YtError(
                    "shuffle=True with joins is not supported yet: the "
                    "gather-merge path would be chosen silently; run the "
                    "join without shuffle or pre-join the table",
                    code=EErrorCode.QueryUnsupported)
            join_setup = self._prepare_joins(plan, table,
                                             foreign_chunks or {})
        if shuffle and plan.group is not None and not plan.group.totals:
            return self._run_shuffled(plan, table)
        n = table.n_shards
        cap = table.capacity
        bottom, front = split_plan(plan)

        rep = table.rep_chunk()
        if join_setup is not None:
            rep = _RepChunk(capacity=cap, columns=join_setup.rep_columns)
        prepared_b = prepare(bottom, rep)
        inter_rep = _RepChunk(
            capacity=n * prepared_b.out_capacity,
            columns={c.name: _RepColumn(type=c.type, dictionary=c.vocab)
                     for c in prepared_b.output})
        prepared_f = prepare(front, inter_rep)

        key = (ir.fingerprint(bottom), ir.fingerprint(front), n, cap,
               prepared_b.binding_shapes(), prepared_f.binding_shapes(),
               join_setup.fingerprint if join_setup else None)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build(prepared_b, prepared_f, cap, join_setup)
            self._cache[key] = fn
        base_names = table.schema.column_names
        columns = {c.name: (table.columns[c.name].data,
                            table.columns[c.name].valid)
                   for c in bottom.schema if c.name in base_names}
        extra = (join_setup.args, tuple(join_setup.bindings)) \
            if join_setup else ()
        out_planes, out_count = fn(columns, table.row_valid,
                                   tuple(prepared_b.bindings),
                                   tuple(prepared_f.bindings), *extra)
        return _assemble_chunk(prepared_f.output, out_planes, out_count)

    def _run_shuffled(self, plan: ir.Query, table: ShardedTable
                      ) -> ColumnarChunk:
        """GROUP BY via key-hash all_to_all: every device ends up owning
        complete groups, so group+having run fully local; only
        order/project/offset/limit merge at the front."""
        from dataclasses import replace as dc_replace

        import numpy as np

        from ytsaurus_tpu.parallel.shuffle import route_rows, transfer_counts
        from ytsaurus_tpu.chunks.columnar import pad_capacity
        from ytsaurus_tpu.query.engine.expr import (
            BindContext, ColumnBinding, EmitContext, ExprBinder, _mix_u64,
            _combine_u64,
        )

        mesh = self.mesh
        n = table.n_shards
        cap = table.capacity

        # Bind where + group-key expressions against the (shared) vocab.
        def bind_keys():
            bind_ctx = BindContext(columns={
                name: ColumnBinding(type=col.type, vocab=col.dictionary)
                for name, col in table.columns.items()})
            binder = ExprBinder(bind_ctx)
            where_b = binder.bind(plan.where) if plan.where is not None else None
            key_b = [binder.bind(item.expr)
                     for item in plan.group.group_items]
            return bind_ctx, where_b, key_b

        bind_ctx, where_b, key_b = bind_keys()
        bindings = tuple(bind_ctx.bindings)
        names = [c.name for c in plan.schema]
        columns_global = {name: (table.columns[name].data,
                                 table.columns[name].valid)
                          for name in names}

        def dest_ids(columns, row_valid, bnd):
            ctx = EmitContext(columns=columns, bindings=bnd, capacity=cap)
            mask = row_valid
            if where_b is not None:
                d, v = where_b.emit(ctx)
                mask = mask & v & d.astype(bool)
            acc = jnp.full(cap, np.uint64(0x9E3779B97F4A7C15), dtype=jnp.uint64)
            for kb in key_b:
                data, valid = kb.emit(ctx)
                h = _mix_u64(data) if data.dtype != jnp.bool_ \
                    else _mix_u64(data.astype(jnp.int8))
                h = jnp.where(valid, h, jnp.zeros_like(h))
                acc = _combine_u64(acc, h)
            pid = (acc % np.uint64(n)).astype(jnp.int32)
            return jnp.where(mask, pid, n), mask

        # Pass 1: transfer matrix → exact quota.
        def count_pass(columns, row_valid, bnd):
            pid, mask = dest_ids(columns, row_valid, bnd)
            return transfer_counts(pid, mask, n)

        counts = jax.jit(shard_map(
            count_pass, mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
            out_specs=P(SHARD_AXIS), check_vma=False))(
                columns_global, table.row_valid, bindings)
        quota = pad_capacity(max(int(np.asarray(counts).max()), 1))
        recv_cap = quota * n

        # Local plan: complete groups per device (group + having only),
        # then the front (order/project/offset/limit) runs ON THE MESH over
        # the all_gathered group rows — no host round-trip (the round-1
        # host-merge contradiction of this module's framing).
        local_plan = dc_replace(plan, order=None, project=None, offset=0,
                                limit=None)
        local_rep = _RepChunk(
            capacity=recv_cap,
            columns={name: _RepColumn(type=col.type, dictionary=col.dictionary)
                     for name, col in table.columns.items()})
        prepared_local = prepare(local_plan, local_rep)
        front = ir.FrontQuery(
            schema=local_plan.post_group_schema(), order=plan.order,
            project=plan.project, offset=plan.offset, limit=plan.limit)
        out_cap = prepared_local.out_capacity
        front_rep = _RepChunk(
            capacity=n * out_cap,
            columns={c.name: _RepColumn(type=c.type, dictionary=c.vocab)
                     for c in prepared_local.output})
        prepared_front = prepare(front, front_rep)

        def exchange_group_front(columns, row_valid, bnd, local_bnd,
                                 front_bnd):
            pid, mask = dest_ids(columns, row_valid, bnd)
            recv, recv_mask = route_rows(columns, pid, n, quota, cap)
            planes, count = prepared_local.run(recv, recv_mask, local_bnd)
            shard_mask = jnp.arange(out_cap) < count
            gathered = {}
            for out_col, (d, v) in zip(prepared_local.output, planes):
                gathered[out_col.name] = (
                    jax.lax.all_gather(d, SHARD_AXIS).reshape(-1),
                    jax.lax.all_gather(v, SHARD_AXIS).reshape(-1))
            g_mask = jax.lax.all_gather(shard_mask, SHARD_AXIS).reshape(-1)
            return prepared_front.run(gathered, g_mask, front_bnd)

        key = ("shuffled", ir.fingerprint(plan), n, cap, quota,
               prepared_local.binding_shapes(),
               prepared_front.binding_shapes())
        fn = self._cache.get(key)
        if fn is None:
            fn = jax.jit(shard_map(
                exchange_group_front, mesh=mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P(), P()),
                out_specs=P(), check_vma=False))
            self._cache[key] = fn
        out_planes, out_count = fn(columns_global, table.row_valid, bindings,
                                   tuple(prepared_local.bindings),
                                   tuple(prepared_front.bindings))
        return _assemble_chunk(prepared_front.output, out_planes,
                               out_count)

    def _prepare_joins(self, plan: ir.Query, table: ShardedTable,
                       foreign_chunks: dict) -> _JoinSetup:
        """Bind every join as a replicated lookup: sort the foreign side
        once on the host device, verify key uniqueness, and return a
        traceable per-shard probe step."""
        from ytsaurus_tpu.query.engine.expr import (
            BindContext, ColumnBinding, EmitContext, ExprBinder,
        )
        from ytsaurus_tpu.query.engine.joins import (
            _bind_keys, _emit_encoded_keys, _lex_searchsorted,
            null_key_mask, sort_foreign_keys,
        )

        cap = table.capacity
        bindings: list = []
        namespace: dict[str, ColumnBinding] = {
            name: ColumnBinding(type=col.type, vocab=col.dictionary)
            for name, col in table.columns.items()}
        rep_columns: dict = {
            name: _RepColumn(type=col.type, dictionary=col.dictionary)
            for name, col in table.columns.items()}
        steps = []          # (self_bound, n_keys, is_left, flat_names, arg_slice)
        args: list = []
        fingerprint_parts = []

        for join in plan.joins:
            foreign = foreign_chunks.get(join.foreign_table)
            if foreign is None:
                raise YtError(
                    f"No data provided for join table "
                    f"{join.foreign_table!r}",
                    code=EErrorCode.QueryExecutionError)
            # Bind self keys against the namespace accumulated so far.
            bind_ctx = BindContext(columns=dict(namespace),
                                   bindings=bindings)
            binder = ExprBinder(bind_ctx)
            self_bound = [binder.bind(e) for e in join.self_equations]
            f_bound = _bind_keys(foreign, join.foreign_schema,
                                 join.foreign_equations, bindings)
            if any(b.vocab is not None for b in self_bound + f_bound):
                raise YtError(
                    "SPMD join on string keys is not supported yet; use "
                    "the host-coordinated path",
                    code=EErrorCode.QueryUnsupported)
            # Host phase: encode + sort the foreign keys, verify unique.
            f_ctx = EmitContext(columns={
                name: (foreign.columns[name].data,
                       foreign.columns[name].valid)
                for name in foreign.schema.column_names},
                bindings=tuple(bindings), capacity=foreign.capacity)
            f_keys = _emit_encoded_keys(f_bound, [None] * len(f_bound),
                                        f_ctx)
            n_foreign = foreign.row_count
            # Host phase cached per (join shape, foreign chunk identity):
            # repeated queries against an unchanged dimension table must
            # not re-sort it or pay the uniqueness-check device sync.
            host_key = ("join-host", ir.fingerprint(ir.Query(
                schema=join.foreign_schema, source=join.foreign_table,
                joins=(join,))), id(foreign), foreign.capacity, n_foreign)
            cached = self._cache.get(host_key)
            if cached is None:
                f_order, f_sorted = sort_foreign_keys(f_keys,
                                                      foreign.row_valid)
                # Unique-key check over adjacent sorted pairs.  Null-keyed
                # rows match nothing, so duplicates among them are fine.
                live = jnp.arange(foreign.capacity) < (n_foreign - 1)
                same = jnp.ones(foreign.capacity, dtype=bool)
                non_null = jnp.ones(foreign.capacity, dtype=bool)
                for v, d in f_sorted:
                    same = same & (v == jnp.roll(v, -1)) & \
                        (d == jnp.roll(d, -1))
                    non_null = non_null & (v > 0)
                unique = not bool(jnp.any(same & live & non_null))
                cached = (f_order, f_sorted, unique)
                self._cache[host_key] = cached
            f_order, f_sorted, unique = cached
            if not unique:
                raise YtError(
                    "SPMD join requires unique foreign join keys "
                    "(lookup-join shape); use the host-coordinated path",
                    code=EErrorCode.QueryUnsupported)
            # Replicated args: sorted key planes + gathered foreign columns.
            arg_start = len(args)
            for v, d in f_sorted:
                args.append(v)
                args.append(d)
            flat_names = []
            for fname in join.foreign_columns:
                fcol = foreign.columns[fname]
                flat = f"{join.alias}.{fname}" if join.alias else fname
                flat_names.append(flat)
                args.append(fcol.data[f_order])
                args.append(fcol.valid[f_order])
                namespace[flat] = ColumnBinding(type=fcol.type,
                                                vocab=fcol.dictionary)
                rep_columns[flat] = _RepColumn(type=fcol.type,
                                               dictionary=fcol.dictionary)
            args.append(jnp.asarray(n_foreign, dtype=jnp.int64))
            steps.append((self_bound, len(f_keys), join.is_left,
                          flat_names, (arg_start, len(args)),
                          foreign.capacity))
            fingerprint_parts.append(
                (ir.fingerprint(ir.Query(schema=join.foreign_schema,
                                         source=join.foreign_table,
                                         joins=(join,))),
                 foreign.capacity, n_foreign > 0))

        join_bindings = tuple(bindings)

        def apply(columns, mask, bnd, join_args):
            for (self_bound, n_keys, is_left, flat_names,
                 (a0, a1), f_cap) in steps:
                sl = join_args[a0:a1]
                f_sorted = [(sl[2 * i], sl[2 * i + 1])
                            for i in range(n_keys)]
                n_foreign = sl[-1]
                ctx = EmitContext(columns=columns, bindings=bnd,
                                  capacity=cap)
                self_keys = _emit_encoded_keys(
                    self_bound, [None] * len(self_bound), ctx)
                lo = _lex_searchsorted(f_sorted, n_foreign, f_cap,
                                       self_keys, "left")
                hi = _lex_searchsorted(f_sorted, n_foreign, f_cap,
                                       self_keys, "right")
                matched = mask & ~null_key_mask(self_keys) & (hi > lo)
                pos = jnp.clip(lo, 0, f_cap - 1)
                columns = dict(columns)
                base = 2 * n_keys
                for i, flat in enumerate(flat_names):
                    fd = sl[base + 2 * i]
                    fv = sl[base + 2 * i + 1]
                    columns[flat] = (fd[pos], fv[pos] & matched)
                if not is_left:
                    mask = matched
            return columns, mask

        return _JoinSetup(apply=apply, bindings=join_bindings,
                          args=tuple(args), rep_columns=rep_columns,
                          fingerprint=tuple(fingerprint_parts))

    def _build(self, prepared_b, prepared_f, cap: int, join_setup=None):
        mesh = self.mesh
        join_apply = join_setup.apply if join_setup is not None else None

        def spmd(columns, row_valid, b_bindings, f_bindings,
                 join_args=(), join_bindings=()):
            if join_apply is not None:
                columns, row_valid = join_apply(columns, row_valid,
                                                join_bindings, join_args)
            planes, count = prepared_b.run(columns, row_valid, b_bindings)
            shard_mask = jnp.arange(prepared_b.out_capacity) < count
            gathered = {}
            for out_col, (d, v) in zip(prepared_b.output, planes):
                gd = jax.lax.all_gather(d, SHARD_AXIS).reshape(-1)
                gv = jax.lax.all_gather(v, SHARD_AXIS).reshape(-1)
                gathered[out_col.name] = (gd, gv)
            g_mask = jax.lax.all_gather(shard_mask, SHARD_AXIS).reshape(-1)
            return prepared_f.run(gathered, g_mask, f_bindings)

        # check_vma=False: outputs ARE replicated (every device computes the
        # same front merge over the all_gathered states), but the checker
        # can't infer that through the gather+sort pipeline.
        n_extra = 2 if join_apply is not None else 0
        mapped = shard_map(
            spmd, mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P())
            + (P(),) * n_extra,
            out_specs=P(), check_vma=False)
        return jax.jit(mapped)
