module ytsaurus-tpu/sdk/go

go 1.20
