"""Operation daemon: scheduler + controller agent split out of the
master process.

Ref model: server/scheduler/ + server/controller_agent/ run separately
from masters — operation storms must not contend with the metadata
mutation path, and a controller crash must not lose operations (revival
from Cypress records + snapshots, master connector re-registration).
"""

import statistics
import time

import pytest

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.remote_client import connect_remote
from ytsaurus_tpu.server.scheduler_daemon import SchedulerClient


@pytest.fixture(scope="module")
def cluster():
    from ytsaurus_tpu.environment import LocalCluster
    with LocalCluster("/tmp/sched_cluster_%d" % time.time(), n_nodes=2,
                      scheduler=True) as c:
        yield c


@pytest.fixture()
def clients(cluster):
    data = connect_remote(cluster.primary_address)
    ops = SchedulerClient(cluster.scheduler_address)
    yield data, ops
    ops.close()
    data.close()


def test_operations_run_in_the_daemon(clients):
    data, ops = clients
    data.write_table("//sd/in", [{"k": i % 5, "v": i} for i in range(50)])
    op_id = ops.run_map("cat", "//sd/in", "//sd/mapped", job_count=3)
    op = ops.wait_operation(op_id)
    assert op["state"] == "completed"
    assert len(data.read_table("//sd/mapped")) == 50
    op_id = ops.run_sort("//sd/in", "//sd/sorted", sort_by=["k"])
    ops.wait_operation(op_id)
    ks = [r["k"] for r in data.read_table("//sd/sorted")]
    assert ks == sorted(ks)
    op_id = ops.run_reduce("cat", "//sd/sorted", "//sd/red",
                           reduce_by=["k"])
    ops.wait_operation(op_id)
    assert len(data.read_table("//sd/red")) == 50
    op_id = ops.run_map_reduce(None, "cat", "//sd/in", "//sd/mr",
                               reduce_by=["k"], partition_count=2)
    ops.wait_operation(op_id)
    assert len(data.read_table("//sd/mr")) == 50
    assert any(o["id"] == op_id for o in ops.list_operations())


def test_failed_operation_error_crosses_the_wire(clients):
    data, ops = clients
    data.write_table("//sd/err/in", [{"k": 1}])
    op_id = ops.run_map("exit 3", "//sd/err/in", "//sd/err/out")
    with pytest.raises(YtError) as ei:
        ops.wait_operation(op_id, timeout=60)
    flat = str(ei.value.to_dict())
    assert "exit code 3" in flat or "exited 3" in flat


def test_abort_stops_daemon_operation(clients):
    data, ops = clients
    data.write_table("//sd/ab/in", [{"k": i} for i in range(8)])
    op_id = ops.run_map("sleep 60; cat", "//sd/ab/in", "//sd/ab/out",
                        rows_per_job=1)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if ops.get_operation(op_id)["state"] == "running":
            break
        time.sleep(0.1)
    out = ops.abort_operation(op_id)
    assert out["state"] == "aborted"
    assert ops.get_operation(op_id)["state"] == "aborted"


def test_kill9_mid_operation_revives_and_completes(cluster, clients):
    """The done-criterion: kill -9 the operation daemon mid-run; the
    restarted daemon revives the operation from its Cypress record +
    stripe snapshots and it completes correctly."""
    data, ops = clients
    rows = [{"k": i, "v": i * 2} for i in range(12)]
    data.write_table("//sd/kill/in", rows)
    # 12 one-row jobs x ~0.4s: plenty of mid-flight window.
    op_id = ops.run_map("sleep 0.4; cat", "//sd/kill/in",
                        "//sd/kill/out", rows_per_job=1)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if ops.get_operation(op_id)["state"] == "running":
            break
        time.sleep(0.05)
    time.sleep(1.0)                         # let some stripes land
    cluster.kill_scheduler()
    cluster.restart_scheduler()
    ops2 = SchedulerClient(cluster.scheduler_address)
    op = ops2.wait_operation(op_id, timeout=180)
    assert op["state"] == "completed"
    got = sorted((r["k"], r["v"])
                 for r in data.read_table("//sd/kill/out"))
    assert got == sorted((r["k"], r["v"]) for r in rows)
    ops2.close()


@pytest.mark.slow   # ~16s latency-under-load guard; tier-1 keeps scheduler
# daemon coverage via the four operation tests above.
def test_master_mutations_stay_fast_under_operation_load(clients):
    """The split's point: an operation storm on the daemon leaves the
    master's mutation path responsive (measured)."""
    data, ops = clients
    data.write_table("//sd/load/in", [{"k": i} for i in range(40)])
    op_id = ops.run_map("sleep 0.2; cat", "//sd/load/in",
                        "//sd/load/out", rows_per_job=1)
    latencies = []
    for i in range(30):
        t0 = time.perf_counter()
        data.set(f"//sd/load/probe{i % 4}", i)
        latencies.append(time.perf_counter() - t0)
    med = statistics.median(latencies)
    worst = max(latencies)
    assert med < 0.5, f"median mutation latency {med:.3f}s under ops load"
    assert worst < 5.0, f"worst mutation latency {worst:.3f}s"
    ops.wait_operation(op_id, timeout=180)
