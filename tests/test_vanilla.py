"""Vanilla (gang) + remote-copy operations.

Ref model: vanilla_controller.cpp:130 (named tasks × job_count, gang
restart discipline — the CHYT-clique hosting primitive) and
controllers/remote_copy_controller.cpp (cross-cluster table pull).
"""

import socket
import time

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.errors import EErrorCode, YtError


@pytest.fixture
def client(tmp_path):
    return connect(str(tmp_path))


def test_vanilla_python_tasks(client):
    def worker(task_name, rank):
        return [{"task": task_name, "rank": rank}]

    op = client.run_vanilla({
        "alpha": {"job_count": 3, "callable": worker},
        "beta": {"job_count": 1, "callable": worker},
    })
    assert op.state == "completed"
    assert op.result["jobs"] == 4
    assert op.result["gang_restarts"] == 0
    assert op.result["task_output_rows"] == {"alpha": 3, "beta": 1}


def test_vanilla_command_output_table(client):
    op = client.run_vanilla({
        "emit": {"job_count": 2,
                 "command": 'echo "{\\"cookie\\": $YT_JOB_COOKIE}"',
                 "output_table_path": "//vanilla_out"},
    })
    assert op.state == "completed"
    rows = client.read_table("//vanilla_out")
    assert sorted(r["cookie"] for r in rows) == [0, 1]


def test_vanilla_gang_restart_on_any_failure(client, tmp_path):
    """One flaky job's failure restarts the WHOLE gang: the steady task
    re-runs too (counted via an append file)."""
    flag = tmp_path / "flag"
    count = tmp_path / "count"
    op = client.run_vanilla({
        "flaky": {"job_count": 1,
                  "command": f'if [ ! -f {flag} ]; then touch {flag}; '
                             f'exit 1; fi'},
        "steady": {"job_count": 1,
                   "command": f'echo run >> {count}'},
    })
    assert op.state == "completed"
    assert op.result["gang_restarts"] == 1
    assert count.read_text().count("run") == 2      # gang-wide restart


def test_vanilla_failing_sibling_condemns_long_lived_mate(client, tmp_path):
    """A failing rank must kill a still-running (long-lived) rank mate —
    the gang wait short-circuits on first casualty instead of waiting for
    every job to exit on its own.  Event-based check (the mate's process
    is dead when run_vanilla returns), not a wall-clock bound: under
    full-suite load an elapsed-time assertion flakes even though the
    short-circuit worked."""
    pidfile = tmp_path / "server.pid"
    with pytest.raises(YtError):
        client.run_vanilla({
            "server": {"job_count": 1,
                       "command": f"echo $$ > {pidfile}; sleep 600"},
            "worker": {"job_count": 1, "command": "exit 1"},
        }, max_gang_restarts=0)
    if not pidfile.exists():
        return        # mate never got a slot: condemned while pending
    # The shell creates the file before the pid hits it: poll briefly
    # so a read in that window doesn't ValueError on empty content.
    content = pidfile.read_text().strip()
    for _ in range(20):
        if content:
            break
        time.sleep(0.1)
        content = pidfile.read_text().strip()
    if not content:
        return        # condemned mid-write; nothing to verify against
    pid = int(content)
    # The kill is asynchronous with run_vanilla's raise; poll for the
    # EVENT (process gone) instead of asserting elapsed time.
    for _ in range(600):
        try:
            import os
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.1)
    else:
        pytest.fail("long-lived rank mate survived the gang casualty")


def test_vanilla_gang_exhausts_restarts(client, tmp_path):
    with pytest.raises(YtError) as ei:
        client.run_vanilla({
            "doomed": {"job_count": 1, "command": "exit 3"},
        }, max_gang_restarts=1)
    assert "exit code 3" in str(ei.value.to_dict())


def test_vanilla_gang_all_or_nothing_slots(client):
    """A gang larger than the slot pool is rejected up front (partial
    acquisition would deadlock)."""
    slots = client.scheduler.job_manager.slots
    with pytest.raises(YtError) as ei:
        client.run_vanilla({
            "big": {"job_count": slots + 1, "command": "true"},
        })
    assert "all-or-nothing" in str(ei.value)


def test_vanilla_hosts_long_lived_server_until_abort(client, tmp_path):
    """The clique pattern: an async vanilla op runs a real TCP server;
    clients talk to it; abort_operation tears it down."""
    port = _free_port()
    script = tmp_path / "server.py"
    script.write_text(
        "import socket, sys\n"
        "s = socket.socket()\n"
        "s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
        "s.bind(('127.0.0.1', int(sys.argv[1])))\n"
        "s.listen(1)\n"
        "while True:\n"
        "    c, _ = s.accept()\n"
        "    c.sendall(b'pong')\n"
        "    c.close()\n")
    op = client.run_vanilla({
        "clique": {"job_count": 1,
                   "command": f"exec python3 {script} {port}"},
    }, sync=False)
    reply = None
    for _ in range(100):                 # server needs a moment to bind
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=1) as conn:
                reply = conn.recv(16)
            break
        except OSError:
            time.sleep(0.1)
    assert reply == b"pong"
    assert op.state == "running"
    client.abort_operation(op.id)
    assert op.state == "aborted"
    # The server process dies with the operation.
    for _ in range(50):
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=0.2):
                pass
            time.sleep(0.1)
        except OSError:
            break
    else:
        pytest.fail("server survived operation abort")
    # The controller thread must not resurrect the op as completed.
    time.sleep(0.3)
    assert op.state == "aborted"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- remote copy ---------------------------------------------------------------


def test_remote_copy_between_clusters(tmp_path):
    from ytsaurus_tpu.environment import LocalCluster
    from ytsaurus_tpu.remote_client import connect_remote

    with LocalCluster(str(tmp_path / "src"), n_nodes=1) as src_cluster:
        src = connect_remote(src_cluster.primary_address)
        rows = [{"k": i, "v": f"r{i}"} for i in range(50)]
        src.write_table("//exports/t", rows)
        src.run_sort("//exports/t", "//exports/sorted", ["k"])
        src.set("//exports/sorted/@note", "from-src")

        dst = connect(str(tmp_path / "dst"))
        op = dst.run_remote_copy(src_cluster.primary_address,
                                 "//exports/sorted", "//imported",
                                 attribute_keys=["note"])
        assert op.state == "completed"
        assert op.result["rows"] == 50
        got = dst.read_table("//imported")
        assert [r["k"] for r in got] == list(range(50))
        assert got[0]["v"] == b"r0"
        assert dst.get("//imported/@sorted_by") == ["k"]
        assert dst.get("//imported/@note") == "from-src"
        # Sorted output feeds a local reduce directly.
        dst.run_reduce(lambda key, g: [{"k": key["k"]}], "//imported",
                       "//red", reduce_by="k")
        assert len(dst.read_table("//red")) == 50
        src.close()


def test_remote_copy_missing_table_fails(client):
    from ytsaurus_tpu.environment import LocalCluster
    import tempfile
    with LocalCluster(tempfile.mkdtemp(), n_nodes=1) as src_cluster:
        with pytest.raises(YtError):
            client.run_remote_copy(src_cluster.primary_address,
                                   "//no/such", "//out")
