"""Daemon entry: `python -m ytsaurus_tpu.server.daemon --role primary|node`.

The multiplexed-binary pattern (ref server/all/main.cpp): one entry point,
role picked by flag.

  primary  — metadata master + tablet host + transaction coordinator +
             scheduler + driver proxy, with chunk data placed on remote
             data nodes (RpcChunkStore) once any register; falls back to a
             local store location until then.
  node     — blob chunk store + journal location, heartbeating to the
             primary.

The bound port is written to <root>/<role>.port for launcher discovery.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time


def _write_port_file(root: str, role: str, port: int) -> None:
    path = os.path.join(root, f"{role}.port")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(port))
    os.replace(tmp, path)


def run_primary(root: str, port: int, replication_factor: int = 2,
                journal_nodes: int = 3,
                bootstrap_timeout: float = 60.0) -> None:
    from ytsaurus_tpu import yson
    from ytsaurus_tpu.client import YtClient, YtCluster
    from ytsaurus_tpu.cypress.master import Master
    from ytsaurus_tpu.cypress.quorum import QuorumWal
    from ytsaurus_tpu.errors import YtError
    from ytsaurus_tpu.rpc import Channel, RetryingChannel, RpcServer
    from ytsaurus_tpu.server.remote_store import RpcChunkStore
    from ytsaurus_tpu.server.services import (
        DriverService,
        NodeTracker,
        NodeTrackerService,
    )

    from ytsaurus_tpu.server.monitoring import MonitoringServer
    from ytsaurus_tpu.server.orchid import OrchidService, default_orchid

    os.makedirs(root, exist_ok=True)
    tracker = NodeTracker()
    # Bootstrap service set first: nodes must be able to register before
    # the master recovers (quorum WAL recovery reads their journals).
    server = RpcServer([NodeTrackerService(tracker)], port=port)
    server.start()
    _write_port_file(root, "primary", server.port)
    orchid = default_orchid()
    orchid.register("/node_tracker/alive", tracker.alive)
    server.add_service(OrchidService(orchid))
    monitoring = MonitoringServer(orchid)
    monitoring.start()
    _write_port_file(root, "primary.monitoring", monitoring.port)
    print(f"primary bootstrap on {server.address}", flush=True)

    # Journal membership is STICKY: chosen once, persisted, reused across
    # restarts so recovery always consults the same journal owners.
    journal_cfg_path = os.path.join(root, "journal_config.yson")
    wanted: list[str] | None = None
    if os.path.exists(journal_cfg_path):
        with open(journal_cfg_path, "rb") as f:
            wanted = [j.decode() if isinstance(j, bytes) else j
                      for j in yson.loads(f.read())["journal_node_ids"]]
    deadline = time.monotonic() + bootstrap_timeout
    chosen: dict[str, str] = {}
    while time.monotonic() < deadline:
        alive = tracker.alive()
        if wanted is not None:
            if all(i in alive for i in wanted):
                chosen = {i: alive[i] for i in wanted}
                break
        elif len(alive) >= journal_nodes:
            chosen = dict(sorted(alive.items())[:journal_nodes])
            break
        time.sleep(0.2)
    else:
        if wanted is not None:
            raise YtError(f"journal nodes {wanted} did not register within "
                          f"{bootstrap_timeout}s")
        # Fewer nodes than asked for: take what registered rather than
        # collapsing to a local-only WAL.  Epoch acquisition needs a
        # strict majority of remotes, so an ODD remote count (default 3)
        # keeps takeover live under one dead journal node; an even count
        # still appends fine but requires all remotes up at takeover.
        alive = tracker.alive()
        if alive and journal_nodes > 0:
            chosen = dict(sorted(alive.items())[:journal_nodes])
            print(f"# only {len(chosen)}/{journal_nodes} journal nodes "
                  f"registered within {bootstrap_timeout}s; using "
                  f"{sorted(chosen)} (membership upgrades after recovery "
                  "as more nodes register)", flush=True)
        else:
            print(f"# no data nodes within {bootstrap_timeout}s; "
                  "falling back to local-only WAL", flush=True)

    def _persist_journal_config(ids: list[str]) -> None:
        tmp = journal_cfg_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(yson.dumps({"journal_node_ids": sorted(ids)},
                               binary=True))
        os.replace(tmp, journal_cfg_path)

    if chosen and wanted is None:
        _persist_journal_config(sorted(chosen))

    master_dir = os.path.join(root, "master")
    os.makedirs(master_dir, exist_ok=True)
    wal = None
    if chosen:
        channels = [RetryingChannel(Channel(addr, timeout=30),
                                    attempts=2, backoff=0.1)
                    for _, addr in sorted(chosen.items())]
        locations = 1 + len(channels)
        # First adoption of this quorum config (we just wrote the journal
        # membership): any existing local log predates the quorum and is
        # authoritative — it seeds the replicas instead of being outvoted
        # by their empty journals.
        wal = QuorumWal(os.path.join(master_dir, Master.CHANGELOG),
                        journal_name="master_wal",
                        remote_channels=channels,
                        quorum=locations // 2 + 1,
                        bootstrap_from_local=(wanted is None))
        print(f"quorum WAL over local + {sorted(chosen)} "
              f"(quorum {locations // 2 + 1}/{locations})", flush=True)
    master = Master(master_dir, wal=wal)
    # A membership persisted while under-strength (slow node startup on a
    # previous boot) upgrades here, AFTER recovery: new locations are
    # seeded with the full committed log before the larger quorum is
    # adopted, so the sticky config never pins the cluster to a degraded
    # journal set forever.
    if wal is not None and len(chosen) < journal_nodes:
        extra = {i: a for i, a in sorted(tracker.alive().items())
                 if i not in chosen}
        extra = dict(list(extra.items())[:journal_nodes - len(chosen)])
        adopted = {}
        for node_id, addr in sorted(extra.items()):
            channel = RetryingChannel(Channel(addr, timeout=30),
                                      attempts=2, backoff=0.1)
            # One node at a time: only nodes the WAL actually KEPT are
            # persisted — a failed catch-up must not become a phantom
            # quorum member that outvotes acknowledged records next boot.
            if wal.extend([channel]) == 1:
                adopted[node_id] = addr
        if adopted:
            chosen.update(adopted)
            _persist_journal_config(sorted(chosen))
            print(f"quorum WAL membership upgraded to "
                  f"{sorted(chosen)} (quorum {wal.quorum})",
                  flush=True)
    # The primary holds NO chunk location of its own: all chunk data lives
    # on data-node processes.
    store = RpcChunkStore(tracker.alive_nodes,
                          replication_factor=replication_factor)
    cluster = YtCluster(root, chunk_store=store, master=master)
    client = YtClient(cluster)
    server.add_service(DriverService(client))
    print(f"primary serving on {server.address}", flush=True)
    threading.Event().wait()       # serve until killed


def run_node(root: str, port: int, primary_address: str,
             node_id: str | None = None) -> None:
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.rpc import Channel, RetryingChannel, RpcServer
    from ytsaurus_tpu.server.services import DataNodeService

    from ytsaurus_tpu.server.monitoring import MonitoringServer
    from ytsaurus_tpu.server.orchid import OrchidService, default_orchid

    os.makedirs(root, exist_ok=True)
    node_id = node_id or os.path.basename(os.path.normpath(root))
    store = FsChunkStore(os.path.join(root, "chunks"))
    service = DataNodeService(store, os.path.join(root, "journals"))
    orchid = default_orchid()
    orchid.register("/data_node", lambda: {
        "id": node_id, "chunk_count": len(store.list_chunks())})
    server = RpcServer([service, OrchidService(orchid)], port=port)
    server.start()
    _write_port_file(root, "node", server.port)
    monitoring = MonitoringServer(orchid)
    monitoring.start()
    _write_port_file(root, "node.monitoring", monitoring.port)
    print(f"data node {node_id} serving on {server.address}", flush=True)

    channel = RetryingChannel(Channel(primary_address, timeout=10),
                              attempts=2, backoff=0.1)
    address = server.address
    while True:
        try:
            channel.call("node_tracker", "heartbeat",
                         {"id": node_id, "address": address})
        except Exception as exc:      # noqa: BLE001 — keep heartbeating
            print(f"# heartbeat to {primary_address} failed: {exc}",
                  file=sys.stderr, flush=True)
        time.sleep(2.0)


def run_proxy(root: str, port: int, primary_address: str) -> None:
    """HTTP proxy daemon: REST /api/v4 bridged to the primary's RPC plane
    (ref: the standalone http_proxy process, server/http_proxy)."""
    from ytsaurus_tpu.remote_client import RemoteYtClient
    from ytsaurus_tpu.server.http_proxy import HttpProxy

    os.makedirs(root, exist_ok=True)
    proxy = HttpProxy(
        lambda user: RemoteYtClient(primary_address, user=user),
        port=port)
    _write_port_file(root, "proxy", proxy.port)
    print(f"http proxy serving on {proxy.address} -> {primary_address}",
          flush=True)
    proxy.serve_forever()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--role", choices=("primary", "node", "proxy"),
                        required=True)
    parser.add_argument("--root", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--primary", default=None,
                        help="primary address (node role)")
    parser.add_argument("--replication-factor", type=int, default=2)
    parser.add_argument("--journal-nodes", type=int, default=3,
                        help="remote WAL locations (0 = local-only WAL); "
                             "odd counts keep takeover live under one "
                             "dead journal node")
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--bootstrap-timeout", type=float, default=60.0)
    args = parser.parse_args()

    # Daemons never touch accelerators; pin CPU before any jax import so a
    # dead tunnel cannot hang a server process.
    import jax
    jax.config.update("jax_platforms", "cpu")

    if args.role == "primary":
        run_primary(args.root, args.port, args.replication_factor,
                    journal_nodes=args.journal_nodes,
                    bootstrap_timeout=args.bootstrap_timeout)
    elif args.role == "proxy":
        if not args.primary:
            parser.error("--primary is required for --role proxy")
        run_proxy(args.root, args.port, args.primary)
    else:
        if not args.primary:
            parser.error("--primary is required for --role node")
        run_node(args.root, args.port, args.primary, node_id=args.node_id)


if __name__ == "__main__":
    main()
