"""RemoteYtClient: the IClient facade over a multi-process cluster.

The thin-client/proxy split (ref rpc_proxy client,
client/api/rpc_proxy/client_impl.h): metadata and tablet commands go to
the primary's DriverService; bulk chunk data moves directly between this
process and the data nodes (RpcChunkStore with the shared rendezvous
placement) — the control/data-plane split of the reference's native
client.  Operations (sort/map/merge/erase) run a local controller against
this client, reading and writing chunks over the node RPC data plane.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.rpc import Channel, RetryingChannel
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.server.remote_store import RpcChunkStore
from ytsaurus_tpu.tablet.timestamp import MAX_TIMESTAMP


@dataclass
class RemoteTransaction:
    id: str
    start_timestamp: int


class RemoteYtClient:
    def __init__(self, primary_address: "str | Sequence[str]",
                 timeout: float = 120.0, user: str = "root"):
        """primary_address: one address, or several (list or
        comma-separated) under multi-master election — the client then
        sticks to whichever master serves and rides out failovers by
        rotating (rpc.FailoverChannel)."""
        if isinstance(primary_address, str):
            addresses = [a.strip() for a in primary_address.split(",")
                         if a.strip()]
        else:
            addresses = list(primary_address)
        self.primary_address = ",".join(addresses)
        self.timeout = timeout
        self.user = user
        if len(addresses) > 1:
            from ytsaurus_tpu.rpc import FailoverChannel
            self._channel = FailoverChannel(addresses, timeout=timeout)
        else:
            self._channel = RetryingChannel(
                Channel(addresses[0], timeout=timeout))
        self.chunk_store = RpcChunkStore(self._alive_nodes)
        from ytsaurus_tpu.operations.scheduler import OperationScheduler
        from ytsaurus_tpu.query.statistics import QueryStatistics
        self.scheduler = OperationScheduler(self)
        self.last_query_statistics = QueryStatistics()

    # -- plumbing --------------------------------------------------------------

    def _alive_nodes(self) -> list[str]:
        body, _ = self._channel.call("node_tracker", "list_nodes", {})
        return [a.decode() if isinstance(a, bytes) else a
                for a in body.get("alive", [])]

    def exec_node_addresses(self) -> dict:
        """id -> address of data nodes hosting exec slots."""
        def _t(x):
            return x.decode() if isinstance(x, bytes) else x
        body, _ = self._channel.call("node_tracker", "list_nodes", {})
        return {_t(k): _t(v) for k, v in (body.get("nodes") or {}).items()}

    def _execute(self, command: str, parameters: Optional[dict] = None,
                 attachments=(), idempotent: bool = True):
        body, out_attachments = self._channel.call(
            "driver", "execute",
            {"command": command, "parameters": parameters or {},
             "user": self.user},
            attachments, idempotent=idempotent)
        if body.get("kind") == "blob":
            return out_attachments[0]
        return body.get("result")

    def close(self) -> None:
        self._channel.close()
        self.chunk_store.close()

    # -- master transactions / locks / security --------------------------------

    def as_user(self, user: str) -> "RemoteYtClient":
        """A view of this cluster authenticated as another principal
        (shares nothing; its own channel)."""
        return RemoteYtClient(self.primary_address, timeout=self.timeout,
                              user=user)

    def start_tx(self, parent: Optional[str] = None) -> str:
        return self._execute("start_tx", {"parent": parent}
                             if parent else {})

    def commit_tx(self, tx: str) -> None:
        self._execute("commit_tx", {"tx": tx}, idempotent=False)

    def abort_tx(self, tx: str) -> None:
        self._execute("abort_tx", {"tx": tx}, idempotent=False)

    def lock(self, path: str, mode: str = "exclusive",
             tx: Optional[str] = None) -> None:
        self._execute("lock", {"path": path, "mode": mode, "tx": tx},
                      idempotent=False)

    def create_user(self, name: str) -> None:
        self._execute("create_user", {"name": name})

    def create_group(self, name: str,
                     members: Optional[list] = None) -> None:
        params = {"name": name}
        if members is not None:
            params["members"] = members
        self._execute("create_group", params)

    def create_account(self, name: str,
                       resource_limits: Optional[dict] = None) -> None:
        params = {"name": name}
        if resource_limits is not None:
            params["resource_limits"] = resource_limits
        self._execute("create_account", params)

    def add_member(self, group: str, member: str) -> None:
        self._execute("add_member", {"group": group, "member": member})

    def check_permission(self, user: str, permission: str,
                         path: str) -> dict:
        return self._execute("check_permission", {
            "user": user, "permission": permission, "path": path})

    # -- orchid ----------------------------------------------------------------

    def get_orchid(self, path: str = "/") -> Any:
        """Live daemon state (ref: orchid_service.h virtual trees)."""
        body, _ = self._channel.call("orchid", "get", {"path": path})
        return body.get("value")

    def list_orchid(self, path: str = "/") -> list[str]:
        body, _ = self._channel.call("orchid", "list", {"path": path})
        return list(body.get("names", []))

    # -- cypress ---------------------------------------------------------------

    def create(self, node_type: str, path: str,
               attributes: Optional[dict] = None, recursive: bool = False,
               ignore_existing: bool = False,
               tx: Optional[str] = None) -> str:
        attributes = dict(attributes or {})
        schema = attributes.get("schema")
        if isinstance(schema, TableSchema):
            attributes["schema"] = schema.to_dict()
        params = {
            "type": node_type, "path": path, "attributes": attributes,
            "recursive": recursive, "ignore_existing": ignore_existing}
        if tx is not None:
            params["tx"] = tx
        return self._execute("create", params, idempotent=False)

    def get(self, path: str, tx: Optional[str] = None) -> Any:
        params = {"path": path}
        if tx is not None:
            params["tx"] = tx
        return self._execute("get", params)

    def set(self, path: str, value: Any, tx: Optional[str] = None) -> None:
        params = {"path": path, "value": value}
        if tx is not None:
            params["tx"] = tx
        self._execute("set", params, idempotent=False)

    def exists(self, path: str) -> bool:
        return bool(self._execute("exists", {"path": path}))

    def list(self, path: str) -> list[str]:
        return list(self._execute("list", {"path": path}))

    def copy(self, src: str, dst: str, recursive: bool = False) -> str:
        return self._execute("copy", {"source_path": src,
                                      "destination_path": dst,
                                      "recursive": recursive},
                             idempotent=False)

    def move(self, src: str, dst: str, recursive: bool = False) -> str:
        return self._execute("move", {"source_path": src,
                                      "destination_path": dst,
                                      "recursive": recursive},
                             idempotent=False)

    def link(self, target: str, link: str, recursive: bool = False) -> str:
        return self._execute("link", {"target_path": target,
                                      "link_path": link,
                                      "recursive": recursive},
                             idempotent=False)

    def remove(self, path: str, recursive: bool = True,
               force: bool = False, tx: Optional[str] = None) -> None:
        params = {"path": path, "recursive": recursive, "force": force}
        if tx is not None:
            params["tx"] = tx
        self._execute("remove", params, idempotent=False)

    def collect_garbage(self) -> int:
        """Server-side sweep.  NOTE: client-local operations in flight are
        invisible to the primary; run this only while idle."""
        return int(self._execute("collect_garbage", {}, idempotent=False))

    # -- static tables ---------------------------------------------------------

    def write_table(self, path: str, rows, append: bool = False,
                    schema=None, format: Optional[str] = None) -> None:
        params: dict = {"path": path, "append": append}
        if schema is not None:
            params["schema"] = (schema.to_dict()
                                if isinstance(schema, TableSchema)
                                else schema)
        attachments = []
        if format is not None:
            params["format"] = format
            attachments = [rows if isinstance(rows, bytes)
                           else bytes(rows)]
        else:
            params["rows"] = [dict(r) if isinstance(r, dict) else list(r)
                              for r in rows]
        self._execute("write_table", params, attachments, idempotent=False)

    def read_table(self, path: str, format: Optional[str] = None):
        params: dict = {"path": path}
        if format is not None:
            params["format"] = format
        return self._execute("read_table", params)

    # -- dynamic tables --------------------------------------------------------

    def mount_table(self, path: str) -> None:
        self._execute("mount_table", {"path": path}, idempotent=False)

    def unmount_table(self, path: str) -> None:
        self._execute("unmount_table", {"path": path}, idempotent=False)

    def freeze_table(self, path: str) -> None:
        self._execute("freeze_table", {"path": path}, idempotent=False)

    def reshard_table(self, path: str, pivot_keys) -> None:
        self._execute("reshard_table",
                      {"path": path,
                       "pivot_keys": [list(k) for k in pivot_keys]},
                      idempotent=False)

    def compact_table(self, path: str) -> None:
        self._execute("compact_table", {"path": path}, idempotent=False)

    def insert_rows(self, path: str, rows: Sequence[dict],
                    tx: Optional[RemoteTransaction] = None,
                    update: bool = False) -> None:
        rows = [dict(r) for r in rows]
        if tx is None:
            self._execute("insert_rows",
                          {"path": path, "rows": rows, "update": update},
                          idempotent=False)
            return
        self._channel.call("driver", "insert_rows_tx",
                           {"tx_id": tx.id, "path": path, "rows": rows,
                            "update": update}, idempotent=False)

    def delete_rows(self, path: str, keys: Sequence[tuple],
                    tx: Optional[RemoteTransaction] = None) -> None:
        wire_keys = [list(k) for k in keys]
        if tx is None:
            self._execute("delete_rows", {"path": path, "keys": wire_keys},
                          idempotent=False)
            return
        self._channel.call("driver", "delete_rows_tx",
                           {"tx_id": tx.id, "path": path,
                            "keys": wire_keys}, idempotent=False)

    def lookup_rows(self, path: str, keys: Sequence[tuple],
                    timestamp: int = MAX_TIMESTAMP,
                    column_names: Optional[Sequence[str]] = None,
                    timeout: Optional[float] = None,
                    pool: Optional[str] = None):
        """Server-side lookups go through the primary's QueryGateway:
        a throttled request comes back as a RequestThrottled-coded error
        whose retry_after hint the RetryingChannel honors; a
        DeadlineExceeded answer is terminal (never retried)."""
        params: dict = {"path": path, "keys": [list(k) for k in keys]}
        if timestamp != MAX_TIMESTAMP:
            params["timestamp"] = timestamp
        if column_names is not None:
            params["column_names"] = list(column_names)
        if timeout is not None:
            params["timeout"] = timeout
        if pool is not None:
            params["pool"] = pool
        return self._execute("lookup_rows", params)

    def select_rows(self, query: str, timeout: Optional[float] = None,
                    pool: Optional[str] = None,
                    explain_analyze: bool = False,
                    params: Optional[Sequence] = None) -> list[dict]:
        req: dict = {"query": query}
        if timeout is not None:
            req["timeout"] = timeout
        if pool is not None:
            req["pool"] = pool
        if explain_analyze:
            # Server-side profile, returned as a plain dict (the span
            # tree lives in the PRIMARY's collector; `yt trace` reads it
            # back through the orchid).
            req["explain_analyze"] = True
        if params is not None:
            # Placeholder (`?`) bindings; vectors ride as JSON lists.
            req["params"] = list(params)
        return self._execute("select_rows", req)

    def nearest_rows(self, path: str, column: str, query_vector, k: int,
                     metric: str = "l2",
                     timestamp: int = MAX_TIMESTAMP,
                     timeout: Optional[float] = None,
                     pool: Optional[str] = None) -> list[dict]:
        req: dict = {"path": path, "column": column,
                     "query_vector": list(query_vector), "k": k}
        if metric != "l2":
            req["metric"] = metric
        if timestamp != MAX_TIMESTAMP:
            req["timestamp"] = timestamp
        if timeout is not None:
            req["timeout"] = timeout
        if pool is not None:
            req["pool"] = pool
        return self._execute("nearest_rows", req)

    def push_queue(self, path: str, rows: Sequence[dict]) -> int:
        return int(self._execute(
            "push_queue", {"path": path, "rows": [dict(r) for r in rows]},
            idempotent=False))

    def pull_queue(self, path: str, offset: int = 0,
                   limit: Optional[int] = None) -> list[dict]:
        params: dict = {"path": path, "offset": offset}
        if limit is not None:
            params["limit"] = limit
        return self._execute("pull_queue", params)

    def trim_rows(self, path: str, trimmed_count: int) -> None:
        self._execute("trim_rows", {"path": path,
                                    "trimmed_row_count": trimmed_count},
                      idempotent=False)

    # -- materialized views (ISSUE 13) -----------------------------------------

    def create_materialized_view(self, name: str, query: str,
                                 source: Optional[str] = None,
                                 target: Optional[str] = None,
                                 pool: str = "views",
                                 batch_rows: Optional[int] = None) -> dict:
        params: dict = {"name": name, "query": query, "pool": pool}
        if source is not None:
            params["source"] = source
        if target is not None:
            params["target"] = target
        if batch_rows is not None:
            params["batch_rows"] = batch_rows
        return self._execute("create_materialized_view", params,
                             idempotent=False)

    def list_views(self) -> list[str]:
        return self._execute("list_views", {})

    def get_view(self, name: str) -> dict:
        return self._execute("get_view", {"name": name})

    def pause_view(self, name: str) -> dict:
        return self._execute("pause_view", {"name": name},
                             idempotent=False)

    def resume_view(self, name: str) -> dict:
        return self._execute("resume_view", {"name": name},
                             idempotent=False)

    def remove_view(self, name: str, drop_target: bool = False) -> None:
        self._execute("remove_view",
                      {"name": name, "drop_target": drop_target},
                      idempotent=False)

    def refresh_view(self, name: str, max_batches: int = 0) -> dict:
        return self._execute("refresh_view",
                             {"name": name, "max_batches": max_batches},
                             idempotent=False)

    # -- transactions ----------------------------------------------------------

    def start_transaction(self) -> RemoteTransaction:
        body, _ = self._channel.call("driver", "start_transaction", {},
                                     idempotent=False)
        return RemoteTransaction(id=body["tx_id"],
                                 start_timestamp=int(
                                     body["start_timestamp"]))

    def commit_transaction(self, tx: RemoteTransaction) -> int:
        body, _ = self._channel.call("driver", "commit_transaction",
                                     {"tx_id": tx.id}, idempotent=False)
        return int(body["commit_timestamp"])

    def abort_transaction(self, tx: RemoteTransaction) -> None:
        self._channel.call("driver", "abort_transaction", {"tx_id": tx.id},
                           idempotent=False)

    # -- operations (local controller, remote data plane) ----------------------

    def run_sort(self, input_path: str, output_path: str, sort_by, **kw):
        return self.scheduler.start_operation(
            "sort", {"input_table_path": input_path,
                     "output_table_path": output_path,
                     "sort_by": list(sort_by), **kw})

    def run_merge(self, input_paths, output_path: str,
                  mode: str = "unordered", **kw):
        return self.scheduler.start_operation(
            "merge", {"input_table_paths": list(input_paths),
                      "output_table_path": output_path, "mode": mode, **kw})

    def run_map(self, mapper: "Callable | str", input_path: str,
                output_path: str, **kw):
        spec = {"input_table_path": input_path,
                "output_table_path": output_path, **kw}
        if isinstance(mapper, str):
            spec["command"] = mapper
        else:
            spec["mapper"] = mapper
        return self.scheduler.start_operation("map", spec)

    def run_erase(self, table_path: str, **kw):
        return self.scheduler.start_operation(
            "erase", {"table_path": table_path, **kw})

    def run_reduce(self, reducer: "Callable | str",
                   input_path: "str | Sequence[str]", output_path: str,
                   reduce_by, **kw):
        spec = {"output_table_path": output_path,
                "reduce_by": reduce_by, **kw}
        if isinstance(input_path, str):
            spec["input_table_path"] = input_path
        else:
            spec["input_table_paths"] = list(input_path)
        if isinstance(reducer, str):
            spec["command"] = reducer
        else:
            spec["reducer"] = reducer
        return self.scheduler.start_operation("reduce", spec)

    def run_map_reduce(self, mapper: "Callable | str | None",
                       reducer: "Callable | str", input_path: str,
                       output_path: str, reduce_by, **kw):
        spec = {"input_table_path": input_path,
                "output_table_path": output_path,
                "reduce_by": reduce_by, **kw}
        if isinstance(mapper, str):
            spec["map_command"] = mapper
        elif mapper is not None:
            spec["mapper"] = mapper
        if isinstance(reducer, str):
            spec["reduce_command"] = reducer
        else:
            spec["reducer"] = reducer
        return self.scheduler.start_operation("map_reduce", spec)

    def run_vanilla(self, tasks: dict, sync: bool = True, **kw):
        return self.scheduler.start_operation(
            "vanilla", {"tasks": tasks, **kw}, sync=sync)

    def run_remote_copy(self, cluster_address: str, input_path: str,
                        output_path: str, **kw):
        return self.scheduler.start_operation("remote_copy", {
            "cluster_address": cluster_address,
            "input_table_path": input_path,
            "output_table_path": output_path, **kw})

    def abort_operation(self, op_id: str):
        return self.scheduler.abort_operation(op_id)

    # -- chunk-level IO for the local operation controllers --------------------

    def _read_table_chunks(self, path: str) -> list[ColumnarChunk]:
        if bool(self.get(path + "/@dynamic")):
            schema = TableSchema.from_dict(self.get(path + "/@schema"))
            rows = self._execute(
                "select_rows",
                {"query": f"* FROM [{path}]"})
            return [ColumnarChunk.from_rows(schema.to_unsorted(),
                                            rows or [])]
        chunk_ids = self.get(path + "/@chunk_ids") or []
        if not chunk_ids:
            schema_dict = self.get(path + "/@schema")
            if schema_dict is None:
                raise YtError(f"Empty table {path!r} has no schema",
                              code=EErrorCode.NoSuchNode)
            schema = TableSchema.from_dict(schema_dict)
            return [ColumnarChunk.from_rows(schema.to_unsorted(), [])]
        return [self.chunk_store.read_chunk(cid) for cid in chunk_ids]

    def _write_table_chunks(self, path: str, chunks: list[ColumnarChunk],
                            sorted_by: Optional[list[str]] = None,
                            schema: Optional[TableSchema] = None) -> None:
        from ytsaurus_tpu.client import publish_table_chunks
        if not self.exists(path):
            attributes: dict = {}
            if schema is not None:
                attributes["schema"] = schema.to_dict()
            self.create("table", path, attributes=attributes,
                        recursive=True)
        publish_table_chunks(self, self.chunk_store, path, chunks,
                             sorted_by=sorted_by, schema=schema)


def connect_remote(primary_address: "str | Sequence[str]"
                   ) -> RemoteYtClient:
    return RemoteYtClient(primary_address)


def routed_client(replicas: "Sequence[tuple]", timeout: float = 120.0,
                  user: str = "root", scrape_period: float = 0.5,
                  start: bool = True):
    """Load-aware multi-replica client (ISSUE 17): one RemoteYtClient
    per serving replica, routed by a ReplicaRouter that scrapes each
    daemon's monitoring `/serving` endpoint (queue depth, hold EWMA,
    brown-out rung) instead of hedging blindly.

    `replicas`: (name, rpc_address, monitor_address) triples — or
    (rpc_address, monitor_address) pairs, where the rpc address doubles
    as the name."""
    from ytsaurus_tpu.query.routing import ReplicaRouter, RoutedYtClient
    router = ReplicaRouter(replicas, scrape_period=scrape_period)
    clients = {r.name: RemoteYtClient(r.address, timeout=timeout,
                                      user=user)
               for r in router.replicas()}
    routed = RoutedYtClient(router, clients)
    if start:
        router.start()
    return routed
