"""SPMD distributed query execution over a device mesh.

The host-coordinated path (query/coordinator.py) loops over shards; this
module is the TPU-native fast path: every shard (tablet analog) lives on its
own device, the bottom query runs as ONE shard_map program, and the front
merge happens on-device via all_gather over ICI — no host round-trip, no bus.

Ref mapping (SURVEY.md §2.8 parallelism table):
  partition-parallel scan  → shard_map over the 'shard' mesh axis
  two-phase aggregation    → per-shard partial states + all_gather + re-group
  (psum applies when group keys are static; the general re-group handles
  arbitrary key sets)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ytsaurus_tpu.chunks.columnar import (
    Column,
    ColumnarChunk,
    unify_dictionaries,
)
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.parallel.mesh import SHARD_AXIS
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.coordinator import split_plan
from ytsaurus_tpu.query.engine.lowering import prepare
from ytsaurus_tpu.schema import EValueType, TableSchema


@dataclass
class _RepColumn:
    """Vocabulary/type carrier used to bind plans without device planes."""
    type: EValueType
    dictionary: Optional[np.ndarray]


@dataclass
class _RepChunk:
    capacity: int
    columns: dict


class ShardedTable:
    """A table partitioned across a device mesh.

    All shards share one schema, one per-shard capacity and ONE unified
    string vocabulary per column (so dictionary codes agree across devices —
    the HBM-staging analog of the reference's in_memory_manager keeping
    chunks resident in a common format, tablet_node/in_memory_manager.h).

    Planes are global arrays of shape (n_shards * capacity,) sharded along
    the mesh axis; each device holds its (capacity,) slice.
    """

    def __init__(self, schema: TableSchema, mesh: Mesh, capacity: int,
                 columns: dict[str, Column], row_counts: list[int],
                 row_valid: jax.Array):
        self.schema = schema
        self.mesh = mesh
        self.capacity = capacity            # per shard
        self.columns = columns              # global sharded planes
        self.row_counts = row_counts
        self.row_valid = row_valid

    @property
    def n_shards(self) -> int:
        return len(self.row_counts)

    @property
    def total_rows(self) -> int:
        return sum(self.row_counts)

    @staticmethod
    def from_chunks(mesh: Mesh, chunks: Sequence[ColumnarChunk]
                    ) -> "ShardedTable":
        n = mesh.devices.size
        if len(chunks) != n:
            raise YtError(f"Need exactly {n} shards for this mesh, "
                          f"got {len(chunks)}",
                          code=EErrorCode.QueryExecutionError)
        schema = chunks[0].schema
        for c in chunks[1:]:
            if c.schema != schema:
                raise YtError("Shard schema mismatch",
                              code=EErrorCode.QueryExecutionError)
        cap = max(c.capacity for c in chunks)
        chunks = [c.with_capacity(cap) for c in chunks]
        shard_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        columns: dict[str, Column] = {}
        for col_schema in schema:
            cols = [c.column(col_schema.name) for c in chunks]
            vocab = None
            if col_schema.type is EValueType.string:
                cols, vocab = unify_dictionaries(cols)
            data = jnp.concatenate([col.data for col in cols])
            valid = jnp.concatenate([col.valid for col in cols])
            data = jax.device_put(data, shard_sharding)
            valid = jax.device_put(valid, shard_sharding)
            columns[col_schema.name] = Column(
                type=col_schema.type, data=data, valid=valid, dictionary=vocab)
        row_valid = jnp.concatenate(
            [jnp.arange(cap) < c.row_count for c in chunks])
        row_valid = jax.device_put(row_valid, shard_sharding)
        return ShardedTable(schema=schema, mesh=mesh, capacity=cap,
                            columns=columns,
                            row_counts=[c.row_count for c in chunks],
                            row_valid=row_valid)

    def rep_chunk(self) -> _RepChunk:
        return _RepChunk(
            capacity=self.capacity,
            columns={name: _RepColumn(type=col.type, dictionary=col.dictionary)
                     for name, col in self.columns.items()})


class DistributedEvaluator:
    """Compiles and caches SPMD (bottom ∘ all_gather ∘ front) programs."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._cache: dict = {}

    def run(self, plan: ir.Query, table: ShardedTable,
            shuffle: Optional[bool] = None) -> ColumnarChunk:
        """Execute a plan SPMD.  `shuffle=True` uses the all_to_all
        repartition path for GROUP BY (ref CoordinateAndExecuteWithShuffle,
        engine_api/coordinator.h:92): rows move to hash(key)-owned devices
        and each device computes its COMPLETE groups — right when group
        cardinality is high (the all_gather merge would replicate heavy
        front work).  Default: gather-merge."""
        if plan.joins:
            raise YtError(
                "SPMD path does not execute joins yet; use "
                "coordinate_and_execute (host-coordinated) for joined plans",
                code=EErrorCode.QueryUnsupported)
        if shuffle and plan.group is not None and not plan.group.totals:
            return self._run_shuffled(plan, table)
        n = table.n_shards
        cap = table.capacity
        bottom, front = split_plan(plan)

        prepared_b = prepare(bottom, table.rep_chunk())
        inter_rep = _RepChunk(
            capacity=n * prepared_b.out_capacity,
            columns={c.name: _RepColumn(type=c.type, dictionary=c.vocab)
                     for c in prepared_b.output})
        prepared_f = prepare(front, inter_rep)

        key = (ir.fingerprint(bottom), ir.fingerprint(front), n, cap,
               prepared_b.binding_shapes(), prepared_f.binding_shapes())
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build(prepared_b, prepared_f, cap)
            self._cache[key] = fn
        columns = {c.name: (table.columns[c.name].data,
                            table.columns[c.name].valid)
                   for c in bottom.schema}
        out_planes, out_count = fn(columns, table.row_valid,
                                   tuple(prepared_b.bindings),
                                   tuple(prepared_f.bindings))
        out_columns: dict[str, Column] = {}
        out_schema_cols = []
        for out_col, (data, valid) in zip(prepared_f.output, out_planes):
            out_schema_cols.append((out_col.name, out_col.type.value))
            out_columns[out_col.name] = Column(
                type=out_col.type, data=data, valid=valid,
                dictionary=out_col.vocab)
        return ColumnarChunk(schema=TableSchema.make(out_schema_cols),
                             row_count=int(out_count), columns=out_columns)

    def _run_shuffled(self, plan: ir.Query, table: ShardedTable
                      ) -> ColumnarChunk:
        """GROUP BY via key-hash all_to_all: every device ends up owning
        complete groups, so group+having run fully local; only
        order/project/offset/limit merge at the front."""
        from dataclasses import replace as dc_replace

        import numpy as np

        from ytsaurus_tpu.parallel.shuffle import route_rows, transfer_counts
        from ytsaurus_tpu.chunks.columnar import pad_capacity
        from ytsaurus_tpu.query.engine.expr import (
            BindContext, ColumnBinding, EmitContext, ExprBinder, _mix_u64,
            _combine_u64,
        )
        from ytsaurus_tpu.query.engine.evaluator import Evaluator

        mesh = self.mesh
        n = table.n_shards
        cap = table.capacity

        # Bind where + group-key expressions against the (shared) vocab.
        def bind_keys():
            bind_ctx = BindContext(columns={
                name: ColumnBinding(type=col.type, vocab=col.dictionary)
                for name, col in table.columns.items()})
            binder = ExprBinder(bind_ctx)
            where_b = binder.bind(plan.where) if plan.where is not None else None
            key_b = [binder.bind(item.expr)
                     for item in plan.group.group_items]
            return bind_ctx, where_b, key_b

        bind_ctx, where_b, key_b = bind_keys()
        bindings = tuple(bind_ctx.bindings)
        names = [c.name for c in plan.schema]
        columns_global = {name: (table.columns[name].data,
                                 table.columns[name].valid)
                          for name in names}

        def dest_ids(columns, row_valid, bnd):
            ctx = EmitContext(columns=columns, bindings=bnd, capacity=cap)
            mask = row_valid
            if where_b is not None:
                d, v = where_b.emit(ctx)
                mask = mask & v & d.astype(bool)
            acc = jnp.full(cap, np.uint64(0x9E3779B97F4A7C15), dtype=jnp.uint64)
            for kb in key_b:
                data, valid = kb.emit(ctx)
                h = _mix_u64(data) if data.dtype != jnp.bool_ \
                    else _mix_u64(data.astype(jnp.int8))
                h = jnp.where(valid, h, jnp.zeros_like(h))
                acc = _combine_u64(acc, h)
            pid = (acc % np.uint64(n)).astype(jnp.int32)
            return jnp.where(mask, pid, n), mask

        # Pass 1: transfer matrix → exact quota.
        def count_pass(columns, row_valid, bnd):
            pid, mask = dest_ids(columns, row_valid, bnd)
            return transfer_counts(pid, mask, n)

        counts = jax.jit(shard_map(
            count_pass, mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
            out_specs=P(SHARD_AXIS), check_vma=False))(
                columns_global, table.row_valid, bindings)
        quota = pad_capacity(max(int(np.asarray(counts).max()), 1))
        recv_cap = quota * n

        # Local plan: complete groups per device (group + having only).
        local_plan = dc_replace(plan, order=None, project=None, offset=0,
                                limit=None)
        local_rep = _RepChunk(
            capacity=recv_cap,
            columns={name: _RepColumn(type=col.type, dictionary=col.dictionary)
                     for name, col in table.columns.items()})
        prepared_local = prepare(local_plan, local_rep)
        front = ir.FrontQuery(
            schema=local_plan.post_group_schema(), order=plan.order,
            project=plan.project, offset=plan.offset, limit=plan.limit)

        def exchange_and_group(columns, row_valid, bnd, local_bnd):
            pid, mask = dest_ids(columns, row_valid, bnd)
            recv, recv_mask = route_rows(columns, pid, n, quota, cap)
            planes, count = prepared_local.run(recv, recv_mask, local_bnd)
            out = {}
            for out_col, (d, v) in zip(prepared_local.output, planes):
                out[out_col.name] = (d[None, :], v[None, :])
            return out, count[None]

        key = ("shuffled", ir.fingerprint(plan), n, cap, quota,
               prepared_local.binding_shapes())
        fn = self._cache.get(key)
        if fn is None:
            fn = jax.jit(shard_map(
                exchange_and_group, mesh=mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
                out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)), check_vma=False))
            self._cache[key] = fn
        out_planes, out_counts = fn(columns_global, table.row_valid, bindings,
                                    tuple(prepared_local.bindings))
        counts_np = [int(c) for c in np.asarray(out_counts)]
        out_cap = prepared_local.out_capacity

        # Assemble per-shard partial chunks, then host front merge.
        partials = []
        inter_schema = front.schema
        for s in range(n):
            cols = {}
            for out_col in prepared_local.output:
                d, v = out_planes[out_col.name]
                cols[out_col.name] = Column(
                    type=out_col.type,
                    data=d.reshape(n, out_cap)[s],
                    valid=v.reshape(n, out_cap)[s],
                    dictionary=out_col.vocab)
            partials.append(ColumnarChunk(
                schema=inter_schema, row_count=counts_np[s], columns=cols))
        from ytsaurus_tpu.chunks.columnar import concat_chunks
        merged = concat_chunks(
            [p.slice_rows(0, p.row_count) for p in partials])
        return Evaluator().run_plan(front, merged)

    def _build(self, prepared_b, prepared_f, cap: int):
        mesh = self.mesh

        def spmd(columns, row_valid, b_bindings, f_bindings):
            planes, count = prepared_b.run(columns, row_valid, b_bindings)
            shard_mask = jnp.arange(prepared_b.out_capacity) < count
            gathered = {}
            for out_col, (d, v) in zip(prepared_b.output, planes):
                gd = jax.lax.all_gather(d, SHARD_AXIS).reshape(-1)
                gv = jax.lax.all_gather(v, SHARD_AXIS).reshape(-1)
                gathered[out_col.name] = (gd, gv)
            g_mask = jax.lax.all_gather(shard_mask, SHARD_AXIS).reshape(-1)
            return prepared_f.run(gathered, g_mask, f_bindings)

        # check_vma=False: outputs ARE replicated (every device computes the
        # same front merge over the all_gathered states), but the checker
        # can't infer that through the gather+sort pipeline.
        mapped = shard_map(
            spmd, mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
            out_specs=P(), check_vma=False)
        return jax.jit(mapped)
