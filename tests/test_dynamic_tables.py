"""Dynamic table tests: MVCC writes/reads, flush/compaction, transactions,
lookup and select integration.

Modeled on the reference integration suite
yt/yt/tests/integration/dynamic_tables/test_sorted_dynamic_tables.py.
"""

import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.chunks.store import FsChunkStore
from ytsaurus_tpu.query import select_rows
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.tablet.tablet import Tablet
from ytsaurus_tpu.tablet.timestamp import MAX_TIMESTAMP
from ytsaurus_tpu.tablet.transactions import TransactionManager

SCHEMA = TableSchema.make([
    ("key", "int64", "ascending"),
    ("value", "string"),
    ("amount", "int64"),
], unique_keys=True)


@pytest.fixture
def tablet(tmp_path):
    return Tablet(SCHEMA, FsChunkStore(str(tmp_path)))


@pytest.fixture
def txm():
    return TransactionManager()


def _insert(txm, tablet, rows):
    tx = txm.start()
    txm.write_rows(tx, tablet, rows)
    return txm.commit(tx)


def test_insert_and_lookup(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "a", "amount": 10},
                          {"key": 2, "value": "b", "amount": 20}])
    rows = tablet.lookup_rows([(1,), (2,), (3,)])
    assert rows[0] == {"key": 1, "value": b"a", "amount": 10}
    assert rows[1] == {"key": 2, "value": b"b", "amount": 20}
    assert rows[2] is None


def test_overwrite_takes_latest(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "old", "amount": 1}])
    _insert(txm, tablet, [{"key": 1, "value": "new", "amount": 2}])
    (row,) = tablet.lookup_rows([(1,)])
    assert row["value"] == b"new" and row["amount"] == 2


def test_snapshot_isolation_timestamps(tablet, txm):
    ts1 = _insert(txm, tablet, [{"key": 1, "value": "v1", "amount": 1}])
    ts2 = _insert(txm, tablet, [{"key": 1, "value": "v2", "amount": 2}])
    (at_ts1,) = tablet.lookup_rows([(1,)], timestamp=ts1)
    (at_ts2,) = tablet.lookup_rows([(1,)], timestamp=ts2)
    (before,) = tablet.lookup_rows([(1,)], timestamp=ts1 - 1)
    assert at_ts1["value"] == b"v1"
    assert at_ts2["value"] == b"v2"
    assert before is None


def test_delete_row(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "x", "amount": 1}])
    tx = txm.start()
    txm.delete_rows(tx, tablet, [(1,)])
    del_ts = txm.commit(tx)
    (row,) = tablet.lookup_rows([(1,)])
    assert row is None
    # But the old version is still visible before the delete.
    (old,) = tablet.lookup_rows([(1,)], timestamp=del_ts - 1)
    assert old["value"] == b"x"


def test_flush_preserves_versions(tablet, txm):
    ts1 = _insert(txm, tablet, [{"key": 1, "value": "v1", "amount": 1}])
    ts2 = _insert(txm, tablet, [{"key": 1, "value": "v2", "amount": 2}])
    chunk_id = tablet.flush()
    assert chunk_id is not None
    assert tablet.active_store.key_count == 0
    (at_ts1,) = tablet.lookup_rows([(1,)], timestamp=ts1)
    (latest,) = tablet.lookup_rows([(1,)])
    assert at_ts1["value"] == b"v1"
    assert latest["value"] == b"v2"


def test_mixed_store_and_chunk_reads(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "flushed", "amount": 1}])
    tablet.flush()
    _insert(txm, tablet, [{"key": 2, "value": "fresh", "amount": 2}])
    rows = tablet.lookup_rows([(1,), (2,)])
    assert rows[0]["value"] == b"flushed"
    assert rows[1]["value"] == b"fresh"
    snapshot = tablet.read_snapshot()
    assert sorted(r["key"] for r in snapshot.to_rows()) == [1, 2]


def test_write_after_flush_overrides_chunk(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "old", "amount": 1}])
    tablet.flush()
    _insert(txm, tablet, [{"key": 1, "value": "new", "amount": 2}])
    (row,) = tablet.lookup_rows([(1,)])
    assert row["value"] == b"new"


def test_compaction_drops_superseded(tablet, txm):
    for i in range(3):
        _insert(txm, tablet, [{"key": 1, "value": f"v{i}", "amount": i}])
    tablet.flush()
    ts_now = txm.timestamps.generate()
    tablet.compact(retention_timestamp=ts_now)
    assert len(tablet.chunk_ids) == 1
    chunk = tablet.chunk_store.read_chunk(tablet.chunk_ids[0])
    assert chunk.row_count == 1          # only the latest version survives
    (row,) = tablet.lookup_rows([(1,)])
    assert row["value"] == b"v2"


def test_compaction_removes_deleted_keys(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "x", "amount": 1}])
    tx = txm.start()
    txm.delete_rows(tx, tablet, [(1,)])
    txm.commit(tx)
    tablet.flush()
    tablet.compact(retention_timestamp=txm.timestamps.generate())
    assert tablet.chunk_ids == []
    (row,) = tablet.lookup_rows([(1,)])
    assert row is None


def test_conflict_detection(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "base", "amount": 0}])
    tx1 = txm.start()
    tx2 = txm.start()
    txm.write_rows(tx1, tablet, [{"key": 1, "value": "a", "amount": 1}])
    txm.write_rows(tx2, tablet, [{"key": 1, "value": "b", "amount": 2}])
    txm.commit(tx1)
    with pytest.raises(YtError) as err:
        txm.commit(tx2)
    assert err.value.code == 1700
    assert tx2.state == "aborted"
    (row,) = tablet.lookup_rows([(1,)])
    assert row["value"] == b"a"


def test_non_conflicting_keys_commit(tablet, txm):
    tx1 = txm.start()
    tx2 = txm.start()
    txm.write_rows(tx1, tablet, [{"key": 1, "value": "a", "amount": 1}])
    txm.write_rows(tx2, tablet, [{"key": 2, "value": "b", "amount": 2}])
    txm.commit(tx1)
    txm.commit(tx2)
    assert len([r for r in tablet.lookup_rows([(1,), (2,)]) if r]) == 2


def test_multi_tablet_transaction_atomic(tmp_path, txm):
    t1 = Tablet(SCHEMA, FsChunkStore(str(tmp_path / "a")), tablet_id="a")
    t2 = Tablet(SCHEMA, FsChunkStore(str(tmp_path / "b")), tablet_id="b")
    tx = txm.start()
    txm.write_rows(tx, t1, [{"key": 1, "value": "x", "amount": 1}])
    txm.write_rows(tx, t2, [{"key": 1, "value": "y", "amount": 2}])
    ts = txm.commit(tx)
    # Same commit timestamp on both participants.
    assert t1.lookup_rows([(1,)], timestamp=ts)[0]["value"] == b"x"
    assert t2.lookup_rows([(1,)], timestamp=ts)[0]["value"] == b"y"
    assert t1.lookup_rows([(1,)], timestamp=ts - 1)[0] is None
    assert t2.lookup_rows([(1,)], timestamp=ts - 1)[0] is None


def test_abort_releases_locks(tablet, txm):
    tx1 = txm.start()
    txm.write_rows(tx1, tablet, [{"key": 1, "value": "a", "amount": 1}])
    txm.abort(tx1)
    tx2 = txm.start()
    txm.write_rows(tx2, tablet, [{"key": 1, "value": "b", "amount": 2}])
    txm.commit(tx2)
    (row,) = tablet.lookup_rows([(1,)])
    assert row["value"] == b"b"


def test_select_over_tablet_snapshot(tablet, txm):
    for i in range(20):
        _insert(txm, tablet, [{"key": i, "value": f"g{i % 3}",
                               "amount": i * 10}])
    tablet.flush()
    _insert(txm, tablet, [{"key": 100, "value": "g0", "amount": 5}])
    snapshot = tablet.read_snapshot()
    out = select_rows(
        "value, sum(amount) AS total FROM [//t] GROUP BY value",
        {"//t": snapshot})
    rows = {r["value"]: r["total"] for r in out.to_rows()}
    assert rows[b"g0"] == sum(i * 10 for i in range(0, 20, 3)) + 5
    assert rows[b"g1"] == sum(i * 10 for i in range(1, 20, 3))


def test_write_missing_value_column_becomes_null(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "full", "amount": 7}])
    _insert(txm, tablet, [{"key": 1, "value": "partial"}])
    (row,) = tablet.lookup_rows([(1,)])
    # Full-row write semantics: unspecified value columns become null.
    assert row == {"key": 1, "value": b"partial", "amount": None}


def test_batch_required_validation_is_all_or_nothing(tablet, txm):
    import dataclasses
    schema = dataclasses.replace(
        SCHEMA, columns=tuple(
            dataclasses.replace(c, required=(c.name == "value"))
            for c in SCHEMA.columns))
    from ytsaurus_tpu.chunks.store import FsChunkStore
    import tempfile
    t = Tablet(schema, FsChunkStore(tempfile.mkdtemp()))
    tx = txm.start()
    with pytest.raises(YtError):
        txm.write_rows(tx, t, [{"key": 1, "value": "ok"},
                               {"key": 2, "value": None}])
    # Nothing was recorded: commit applies zero rows.
    txm.commit(tx)
    assert t.lookup_rows([(1,), (2,)]) == [None, None]


def test_commit_to_unmounted_participant_applies_nothing(tmp_path, txm):
    t1 = Tablet(SCHEMA, FsChunkStore(str(tmp_path / "x")), tablet_id="x")
    t2 = Tablet(SCHEMA, FsChunkStore(str(tmp_path / "y")), tablet_id="y")
    tx = txm.start()
    txm.write_rows(tx, t1, [{"key": 1, "value": "a", "amount": 1}])
    txm.write_rows(tx, t2, [{"key": 2, "value": "b", "amount": 2}])
    t2.mounted = False
    with pytest.raises(YtError):
        txm.commit(tx)
    # Atomicity: the mounted participant must not have applied either.
    assert t1.lookup_rows([(1,)]) == [None]
    # And locks are free for a new transaction.
    t2.mounted = True
    tx2 = txm.start()
    txm.write_rows(tx2, t1, [{"key": 1, "value": "c", "amount": 3}])
    txm.commit(tx2)
    assert t1.lookup_rows([(1,)])[0]["value"] == b"c"


def test_lookup_row_cache(tablet, txm):
    _insert(txm, tablet, [{"key": i, "value": f"v{i}", "amount": i}
                          for i in range(10)])
    tablet.flush()
    r1 = tablet.lookup_rows([(3,)])[0]
    assert tablet.row_cache_misses >= 1
    hits0 = tablet.row_cache_hits
    r2 = tablet.lookup_rows([(3,)])[0]
    assert tablet.row_cache_hits == hits0 + 1
    assert r1 == r2
    # Writes invalidate: a new value must be visible immediately.
    _insert(txm, tablet, [{"key": 3, "value": "fresh", "amount": 99}])
    assert tablet.lookup_rows([(3,)])[0]["value"] == b"fresh"
    # Column projection applies after the cache (full row cached).
    narrow = tablet.lookup_rows([(3,)], column_names=["amount"])[0]
    assert narrow == {"amount": 99}
    # Timestamped (historical) reads bypass the cache.
    ts_hit = tablet.row_cache_hits
    tablet.lookup_rows([(3,)], timestamp=1)
    assert tablet.row_cache_hits == ts_hit
