"""CLI driver: `python -m tools.analyze` (what `yt analyze` wraps).

Exit codes: 0 clean against the committed baseline, 1 findings violate
the ratchet, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from tools import analyze
    from tools.analyze import lock_discipline

    parser = argparse.ArgumentParser(
        prog="yt analyze",
        description="AST-based static analysis: lock discipline, "
                    "annotation-free guard inference + atomicity lint "
                    "(guards), JAX recompile/host-sync hazards, "
                    "failpoint & span coverage, error taxonomy, sensor "
                    "catalog.")
    parser.add_argument("--root", default=repo_root,
                        help="repo root (contains ytsaurus_tpu/)")
    parser.add_argument("--pass", dest="passes", action="append",
                        choices=sorted(analyze.PASSES),
                        help="run only this pass (repeatable; "
                             "default: all)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings + ratchet "
                             "verdict + lock-order graph")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite tools/analyze/baseline.json to "
                             "the current finding counts (run AFTER "
                             "fixing findings to tighten the ratchet)")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: committed one)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report raw findings; exit 1 if any exist")
    args = parser.parse_args(argv)

    files = analyze.load_files(args.root)
    findings = analyze.run_passes(files, only=args.passes,
                                  root=args.root)

    if args.update_baseline:
        counts = analyze.write_baseline(
            findings, args.baseline or analyze.BASELINE_PATH)
        print(f"baseline updated: {sum(counts.values())} finding(s) "
              f"across {len(counts)} (pass, rule, path) key(s)")
        return 0

    if args.no_baseline:
        violations = [f.format() for f in findings]
    else:
        baseline = analyze.load_baseline(args.baseline)
        violations = analyze.check_ratchet(findings, baseline)

    if args.json:
        payload = {
            "findings": [f.to_dict() for f in findings],
            "violations": violations,
            "counts": analyze.aggregate(findings),
            "lock_order": lock_discipline.order_graph_snapshot(files),
            "clean": not violations,
        }
        if args.passes is None or "guards" in args.passes:
            # ISSUE 15: the guards pass's superset graph — what the
            # runtime sanitizer's dynamic⊆static gate checks against —
            # plus the register_lock site → static-node map.  Scoped to
            # guards runs: the deep closure is the expensive part.
            from tools.analyze import guard_inference
            payload["reconciliation"] = \
                guard_inference.reconciliation_graph(files)
        print(json.dumps(payload, indent=2))
        return 1 if violations else 0

    for violation in violations:
        print(violation, file=sys.stderr)
    if violations:
        print(f"{len(violations)} static-analysis violation(s) "
              f"({len(findings)} finding(s) total; baseline ratchet: "
              f"counts may only decrease)", file=sys.stderr)
        return 1
    suffix = f", {len(findings)} baselined finding(s)" if findings else ""
    print(f"static analysis clean{suffix}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
