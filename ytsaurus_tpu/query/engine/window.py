"""Window-function execution: segmented prefix scans over partition-sorted
planes.

The reference query engine has no window functions (the layer-6 gap in
VERDICT.md); databases that JIT them stream each partition through a
stateful per-row loop.  The TPU lowering instead turns the whole stage
into the backbone's strongest primitive — ONE u32 packed sort bringing
equal PARTITION BY keys adjacent (ordered by the ORDER BY spec inside
each partition), then every window item is a segmented prefix scan,
shifted gather, or scan-difference over the sorted planes:

  row_number        position scan (iota - segment start index)
  rank              peer-boundary running max
  dense_rank        segmented cumsum of peer boundaries
  lag / lead        within-segment shifted gather
  first/last_value  gather at the frame boundary row
  sum/count/avg     inclusive segmented cumsum, ROWS frame = P[hi] - P[lo-1]
  min / max         prefix/suffix scans, or a doubling-table range query
                    for two-sided bounded frames

Results scatter back to the original row order through the inverse
permutation, so the stage ADDS columns without moving rows — filter,
ORDER BY and projection downstream see the input rowset unchanged.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.ops.segments import (
    packed_sort_indices,
    segment_end_index,
    segment_position,
    segment_range_extreme,
    segment_scan,
    segment_shift,
    segment_start_index,
    segment_suffix_scan,
)
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.engine.expr import (
    ColumnBinding,
    EmitContext,
    ExprBinder,
    _gather_binding,
    _merge_vocabs,
    _pad_np,
    _remap_table,
    _vocab_bucket,
)
from ytsaurus_tpu.query.engine.lowering import _order_key_bits
from ytsaurus_tpu.schema import EValueType, device_dtype


class WindowStage:
    """Host-bound window stage for one chunk: binds partition/order/item
    expressions (appending vocabulary tables to the shared bindings
    list), exposes the slot column bindings for downstream reference
    resolution, and emits the traced computation."""

    def __init__(self, window: ir.WindowClause, binder: ExprBinder):
        self.window = window
        self.partition_b = [binder.bind(item.expr)
                            for item in window.partition_items]
        self.order_b = [(binder.bind(oi.expr), oi.descending)
                        for oi in window.order_items]
        self.items_b = []
        for item in window.items:
            arg = binder.bind(item.argument) \
                if item.argument is not None else None
            dflt = binder.bind(item.default) \
                if item.default is not None else None
            # String lag/lead with a string default: both planes must
            # land in ONE code space — merge vocabularies host-side and
            # remap through bound tables (the if/if_null pattern).
            vocab = None
            arg_gather = dflt_gather = None
            if item.type is EValueType.string:
                vocab = arg.vocab
                if dflt is not None and dflt.type is EValueType.string:
                    vocab = _merge_vocabs(arg.vocab, dflt.vocab)
                    for side in (arg, dflt):
                        side_vocab = side.vocab if side.vocab is not None \
                            else np.array([], dtype=object)
                        table = _remap_table(side_vocab, vocab)
                        slot = binder.ctx.add(jnp.asarray(_pad_np(
                            table, _vocab_bucket(max(len(side_vocab), 1)),
                            0)))
                        if side is arg:
                            arg_gather = _gather_binding(slot)
                        else:
                            dflt_gather = _gather_binding(slot)
            self.items_b.append((item, arg, dflt, vocab,
                                 arg_gather, dflt_gather))

    def slot_bindings(self) -> dict[str, ColumnBinding]:
        return {item.name: ColumnBinding(type=item.type, vocab=vocab)
                for item, _, _, vocab, _, _ in self.items_b}

    # -- trace-time ------------------------------------------------------------

    def emit(self, ctx: EmitContext, mask: jax.Array
             ) -> dict[str, tuple[jax.Array, jax.Array]]:
        """Compute every window column; returns slot planes in the
        ORIGINAL row order (validity already restricted to `mask`)."""
        n = ctx.capacity
        iota = jnp.arange(n, dtype=jnp.int32)

        # One packed sort: masked-last, then partition keys (ascending,
        # groups only need adjacency), then the ORDER BY spec.
        sort_items = [((~mask), jnp.ones_like(mask), False, 1)]
        part_planes = [b.emit(ctx) for b in self.partition_b]
        for b, (d, v) in zip(self.partition_b, part_planes):
            sort_items.append((d, v, False, _order_key_bits(b)))
        order_planes = [b.emit(ctx) for b, _ in self.order_b]
        for (b, descending), (d, v) in zip(self.order_b, order_planes):
            sort_items.append((d, v, descending, _order_key_bits(b)))
        order_idx = packed_sort_indices(sort_items)
        inv = jnp.zeros(n, dtype=jnp.int32).at[order_idx].set(iota)

        s_mask = mask[order_idx]
        # Segment starts: row 0, any partition-key change, and the
        # unmasked→masked transition (so the trailing masked rows never
        # extend a real partition's frame range).
        starts = jnp.zeros(n, dtype=bool).at[0].set(True)
        starts = starts | (s_mask != jnp.roll(s_mask, 1))
        for d, v in part_planes:
            sd, sv = d[order_idx], v[order_idx]
            starts = starts | (sd != jnp.roll(sd, 1)) | \
                (sv != jnp.roll(sv, 1))
        starts = starts.at[0].set(True)
        # Peer boundaries: a new segment or any ORDER BY key change.
        peers = starts
        for (b, _), (d, v) in zip(self.order_b, order_planes):
            sd, sv = d[order_idx], v[order_idx]
            peers = peers | (sd != jnp.roll(sd, 1)) | \
                (sv != jnp.roll(sv, 1))
        peers = peers.at[0].set(True)

        seg_lo = segment_start_index(starts)
        seg_hi = segment_end_index(starts)
        # Last row of each ORDER-BY peer group (peers is itself a starts
        # plane over the peer segmentation, and partition starts always
        # open a peer group, so peer ends never cross partitions).  Used
        # by the standard default frame (RANGE-peers end).
        peer_end = None
        if any(item.frame[2] == "peer" for item, *_ in self.items_b):
            peer_end = segment_end_index(peers)

        out: dict[str, tuple[jax.Array, jax.Array]] = {}
        for item, arg, dflt, vocab, arg_gather, dflt_gather in self.items_b:
            data, valid = self._emit_item(
                ctx, item, arg, dflt, arg_gather, dflt_gather,
                order_idx, s_mask, starts, peers, seg_lo, seg_hi,
                peer_end, iota)
            out[item.name] = (data[inv], valid[inv] & mask)
        return out

    def _frame_range(self, item: ir.WindowItem, seg_lo, seg_hi, peer_end,
                     iota):
        lo_kind, lo_off, hi_kind, hi_off = item.frame
        lo = seg_lo if lo_kind == "unbounded" else \
            jnp.maximum(seg_lo, iota + lo_off)
        if hi_kind == "unbounded":
            hi = seg_hi
        elif hi_kind == "peer":
            hi = peer_end
        else:
            hi = jnp.minimum(seg_hi, iota + hi_off)
        return lo, hi, lo > hi

    def _emit_item(self, ctx, item, arg, dflt, arg_gather, dflt_gather,
                   order_idx, s_mask, starts, peers, seg_lo, seg_hi,
                   peer_end, iota):
        fn = item.function
        n = s_mask.shape[0]

        if fn == "row_number":
            pos = segment_position(starts)
            return (pos + 1).astype(jnp.int64), jnp.ones(n, dtype=bool)
        if fn == "rank":
            peer_start = jax.lax.associative_scan(
                jnp.maximum, jnp.where(peers, iota, jnp.zeros_like(iota)))
            return (peer_start - seg_lo + 1).astype(jnp.int64), \
                jnp.ones(n, dtype=bool)
        if fn == "dense_rank":
            dr = segment_scan("sum", peers.astype(jnp.int64), starts)
            return dr, jnp.ones(n, dtype=bool)

        a_data, a_valid = arg.emit(ctx)
        a_data = a_data[order_idx]
        a_valid = a_valid[order_idx] & s_mask
        if arg_gather is not None:
            a_data = arg_gather(ctx, a_data)

        if fn in ("lag", "lead"):
            shift = item.offset if fn == "lag" else -item.offset
            sh_d, sh_v, in_seg = segment_shift(a_data, a_valid, starts,
                                               shift, seg_lo=seg_lo,
                                               seg_hi=seg_hi)
            if dflt is not None:
                d_data, d_valid = dflt.emit(ctx)
                d_data = d_data[order_idx]
                d_valid = d_valid[order_idx]
                if dflt_gather is not None:
                    d_data = dflt_gather(ctx, d_data)
                sh_d, d_data = _promote_window_pair(sh_d, d_data)
                data = jnp.where(in_seg, sh_d, d_data)
                valid = jnp.where(in_seg, sh_v, d_valid)
            else:
                data = sh_d
                valid = sh_v & in_seg
            return data, valid

        lo, hi, empty = self._frame_range(item, seg_lo, seg_hi, peer_end,
                                          iota)
        lo_c = jnp.clip(lo, 0, n - 1)
        hi_c = jnp.clip(hi, 0, n - 1)

        if fn == "first_value":
            return a_data[lo_c], a_valid[lo_c] & ~empty
        if fn == "last_value":
            return a_data[hi_c], a_valid[hi_c] & ~empty

        # Framed aggregates: count of contributing rows first (validity
        # for every other aggregate, the result for count itself).
        cnt_scan = segment_scan("sum", a_valid.astype(jnp.int64), starts)
        cnt = cnt_scan[hi_c] - jnp.where(
            lo > seg_lo, cnt_scan[jnp.clip(lo - 1, 0, n - 1)],
            jnp.zeros_like(cnt_scan))
        cnt = jnp.where(empty, jnp.zeros_like(cnt), cnt)
        if fn == "count":
            return cnt, jnp.ones(n, dtype=bool)

        if fn in ("sum", "avg"):
            acc_dtype = jnp.float64 if fn == "avg" else \
                device_dtype(item.type)
            contrib = jnp.where(a_valid, a_data.astype(acc_dtype),
                                jnp.zeros(n, dtype=acc_dtype))
            p = segment_scan("sum", contrib, starts)
            total = p[hi_c] - jnp.where(
                lo > seg_lo, p[jnp.clip(lo - 1, 0, n - 1)],
                jnp.zeros_like(p))
            if fn == "avg":
                total = total / jnp.maximum(cnt, 1)
            return total, cnt > 0

        if fn in ("min", "max"):
            lo_kind, _, hi_kind, _ = item.frame
            if lo_kind == "unbounded" and hi_kind == "unbounded":
                scan = segment_scan(fn, _neutralized(a_data, a_valid, fn),
                                    starts)
                data = scan[seg_hi]
            elif lo_kind == "unbounded":
                scan = segment_scan(fn, _neutralized(a_data, a_valid, fn),
                                    starts)
                data = scan[hi_c]
            elif hi_kind == "unbounded":
                scan = segment_suffix_scan(
                    fn, _neutralized(a_data, a_valid, fn), starts)
                data = scan[lo_c]
            else:
                _, lo_off, _, hi_off = item.frame
                data = segment_range_extreme(
                    fn, a_data, a_valid, lo_c, jnp.maximum(hi_c, lo_c),
                    max_width=hi_off - lo_off + 1)
            if item.type is EValueType.boolean:
                data = data.astype(jnp.bool_)
            return data, cnt > 0

        raise YtError(f"Window function {fn!r} has no lowering",
                      code=EErrorCode.QueryUnsupported)


def _neutralized(data: jax.Array, valid: jax.Array, fn: str) -> jax.Array:
    from ytsaurus_tpu.ops.segments import _reduce_neutral
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int8)
    return jnp.where(valid, data, _reduce_neutral(data.dtype, fn))


def _promote_window_pair(a: jax.Array, b: jax.Array):
    if a.dtype == b.dtype:
        return a, b
    target = jnp.promote_types(a.dtype, b.dtype)
    return a.astype(target), b.astype(target)
