"""Job execution plane: worker slots, user-job proxies, speculation,
preemption.

Ref shape:
  exec_node slot manager + job controller  (server/node/exec_node/) —
    N worker slots run jobs scheduled onto them;
  job proxy user jobs (server/job_proxy/user_job.cpp) — user code runs in
    a SEPARATE process, rows piped through wire formats on stdin/stdout,
    stderr tail captured onto the job;
  speculative jobs (controllers/speculative_job_manager.h) — a straggler
    gets a duplicate; first finisher wins, the loser is aborted;
  preemption (scheduler strategy) — jobs of pools above fair share abort
    to unblock starving pools.

Redesign: slots are threads (the compute inside a job is a jitted device
program or a child process, so Python threads don't serialize the real
work).  Command jobs run `/bin/sh -c <command>` with formatted rows on
stdin — arbitrary user binaries work, isolation is process-level.
Python-callable jobs run in-slot (they cannot be killed, so they are
neither preemptible nor speculated; command jobs are both).
"""

from __future__ import annotations

import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.operations.fair_share import (
    PoolState,
    compute_fair_shares,
    find_preemptable,
    pick_pool,
)
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.logging import get_logger
from ytsaurus_tpu.utils.profiling import Profiler

logger = get_logger("Jobs")
_profiler = Profiler("/jobs")

STDERR_TAIL_BYTES = 16 << 10


def _job_error(site: str) -> YtError:
    return YtError(f"injected job fault at {site}",
                   code=EErrorCode.OperationFailed,
                   attributes={"failpoint": site})


# Execution-plane fault sites: start/finish bracket the user code (an
# injected error is a job failure, exercising the retry quarantine);
# worker_death in crash-once mode kills the slot THREAD mid-job — the
# manager must requeue the orphaned job and respawn the slot.
_FP_START = failpoints.register_site("jobs.start", error=_job_error)
_FP_FINISH = failpoints.register_site("jobs.finish", error=_job_error)
_FP_WORKER_DEATH = failpoints.register_site("jobs.worker_death",
                                            error=_job_error)


@dataclass
class Job:
    """One schedulable unit.  `run` does the work (already bound to its
    input stripe); command jobs also set `command` so the manager can
    kill/speculate them."""

    op_id: str
    index: int
    run: Callable[["Job"], object]
    pool: str = "default"
    preemptible: bool = False        # command jobs: killable + restartable
    id: str = field(default_factory=lambda: uuid.uuid4().hex[:16])
    state: str = "pending"           # pending|running|completed|failed|aborted
    result: object = None
    error: Optional[YtError] = None
    attempt: int = 0
    started_at: float = 0.0
    duration: float = 0.0
    stderr_tail: bytes = b""
    speculative_of: "Optional[Job]" = None
    on_done: Optional[Callable[["Job"], None]] = None
    # Job splitter (ref job_splitter.h): returns child jobs covering this
    # job's remaining input; the manager kills the straggler and settles
    # it from the children's results (in index order).
    splitter: "Optional[Callable[['Job'], list['Job']]]" = None
    split_children: "Optional[list['Job']]" = None
    # Split children run half-sized inputs: their durations must not feed
    # the straggler median, or healthy full-size jobs start "straggling".
    record_duration: bool = True
    # Failure quarantine (ref max_failed_job_count): a failing run with
    # failures < max_failures requeues instead of settling failed, so
    # transient faults (node death, injected error) don't fail the
    # operation on the first casualty.  `failures` counts GENUINE failed
    # runs only — preemption/worker-death/split requeues bump `attempt`
    # (address rotation) but must not burn the failure budget.
    max_failures: int = 1
    failures: int = 0
    _split_pending: bool = False     # chosen for split; blocks speculation
    # live process handle for kill-based preemption/speculation-loss
    _proc: Optional[subprocess.Popen] = None
    # (address, remote_job_id) while running on an exec node
    _remote: "Optional[tuple[str, str]]" = None
    _done: threading.Event = field(default_factory=threading.Event)
    _lost: bool = False              # lost the speculative race
    _preempted: bool = False         # killed for fairness; will requeue


class JobManager:
    """Slots + fair-share pick + speculation + preemption for one process.

    Operations submit job lists and wait; the manager schedules across
    ALL live operations by pool fair share.
    """

    def __init__(self, slots: int = 4,
                 speculation_factor: float = 3.0,
                 min_speculation_seconds: float = 5.0,
                 pool_config: Optional[Callable[[str], dict]] = None,
                 slot_ban_after: int = 5,
                 slot_ban_seconds: float = 2.0):
        self.slots = slots
        self.speculation_factor = speculation_factor
        self.min_speculation_seconds = min_speculation_seconds
        # Slot quarantine: a slot whose last `slot_ban_after` runs ALL
        # failed is probably sitting on broken local state (bad disk,
        # leaked cgroup) — it cools off for `slot_ban_seconds` before
        # taking more work instead of chewing through the queue.  The
        # signal can't distinguish a poisoned slot from a poisoned queue
        # (one op mass-failing); that's accepted: the short cooldown then
        # acts as failure-storm throttling, bounded at slot_ban_seconds
        # per slot_ban_after failures per slot.
        self.slot_ban_after = slot_ban_after
        self.slot_ban_seconds = slot_ban_seconds
        self._pool_config = pool_config or (lambda name: {})
        # Config lookups may be Cypress RPCs; they run OUTSIDE the lock
        # (submit + monitor refresh this cache; scheduling reads it).
        self._pool_cfg_cache: dict[str, dict] = {}
        self._lock = threading.Condition()
        self._pending: list[Job] = []
        self._running: list[Job] = []
        self._workers: list[threading.Thread] = []
        self._monitor: Optional[threading.Thread] = None
        self._stop = False
        self._completed_durations: dict[str, list[float]] = {}
        self._split_parents: list[Job] = []

    # -- public ----------------------------------------------------------------

    def submit(self, jobs: "list[Job]") -> None:
        self._refresh_pool_configs({j.pool for j in jobs})
        with self._lock:
            self._pending.extend(jobs)
            self._ensure_workers()
            self._lock.notify_all()

    def _refresh_pool_configs(self, names) -> None:
        """Fetch pool configs WITHOUT holding the scheduling lock (they
        may be remote RPCs; a dead primary must not freeze the slots)."""
        for name in names:
            try:
                self._pool_cfg_cache[name] = self._pool_config(name) or {}
            except Exception:   # noqa: BLE001 — config must not fail jobs
                self._pool_cfg_cache.setdefault(name, {})

    def wait(self, jobs: "list[Job]", timeout: Optional[float] = None,
             raise_on_failure: bool = True) -> None:
        deadline = time.monotonic() + timeout if timeout else None
        for job in jobs:
            remaining = None if deadline is None else \
                max(deadline - time.monotonic(), 0.0)
            if not job._done.wait(remaining):
                raise YtError(f"Job {job.id} timed out",
                              code=EErrorCode.Timeout)
        if raise_on_failure:
            for job in jobs:
                if job.state == "failed":
                    raise job.error or YtError(
                        f"Job {job.id} failed",
                        code=EErrorCode.OperationFailed)

    def run_all(self, jobs: "list[Job]",
                timeout: Optional[float] = None) -> "list[object]":
        """Submit + wait; results in submission order (speculative winners
        folded in)."""
        self.submit(jobs)
        self.wait(jobs, timeout=timeout)
        return [j.result for j in jobs]

    def abort_operation(self, op_id: str) -> None:
        with self._lock:
            dropped = [j for j in self._pending if j.op_id == op_id]
            self._pending = [j for j in self._pending if j.op_id != op_id]
            for job in dropped:
                # Waiters may hold these: they must observe a terminal
                # state, not hang on a job that will never run.
                job.state = "aborted"
                job.error = YtError("operation aborted",
                                    code=EErrorCode.Canceled)
                job._done.set()
            for job in self._running:
                if job.op_id == op_id:
                    self._kill(job)
            for parent in list(self._split_parents):
                if parent.op_id == op_id:
                    parent.state = "aborted"
                    parent.error = YtError("operation aborted",
                                           code=EErrorCode.Canceled)
                    parent._done.set()
                    self._split_parents.remove(parent)
            self._completed_durations.pop(op_id, None)
            self._lock.notify_all()

    def finish_operation(self, op_id: str) -> None:
        """Drop per-operation bookkeeping once its jobs are settled (the
        duration history otherwise grows forever in a long-lived client)."""
        with self._lock:
            self._completed_durations.pop(op_id, None)

    def stats(self) -> dict:
        with self._lock:
            return {"pending": len(self._pending),
                    "running": len(self._running),
                    "slots": self.slots}

    # -- scheduling ------------------------------------------------------------

    def _ensure_workers(self) -> None:
        # Prune dead slots (worker-death crashes) before topping up, or a
        # crashed slot would count against the budget forever.
        self._workers = [w for w in self._workers
                         if w.is_alive() or w is threading.current_thread()
                         or not w.ident]
        while len(self._workers) < self.slots:
            worker = threading.Thread(target=self._worker_loop, daemon=True,
                                      name=f"job-slot-{len(self._workers)}")
            self._workers.append(worker)
            worker.start()
        if self._monitor is None:
            # Speculation + preemption must fire even when EVERY slot is
            # busy (exactly the starvation case), so they run on their own
            # cadence, not only from idle workers.
            self._monitor = threading.Thread(target=self._monitor_loop,
                                             daemon=True,
                                             name="job-monitor")
            self._monitor.start()

    def _monitor_loop(self) -> None:
        last_refresh = 0.0
        while not self._stop:
            time.sleep(0.25)
            now = time.monotonic()
            if now - last_refresh > 5.0:
                with self._lock:
                    names = {j.pool for j in self._pending + self._running}
                self._refresh_pool_configs(names)   # outside the lock
                last_refresh = now
            to_split: list[Job] = []
            settled: list[Job] = []
            with self._lock:
                try:
                    self._ensure_workers()   # heal crash-killed slots
                    to_split = self._split_candidates_locked()
                    self._maybe_speculate_locked()
                    self._maybe_preempt_locked()
                    settled = self._settle_splits_locked()
                except Exception:   # noqa: BLE001 — monitor must survive
                    logger.exception("job monitor pass failed")
            # User splitters and on_done observers may do RPCs/chunk IO —
            # NEVER under the scheduling lock (every slot would stall).
            for job in to_split:
                self._perform_split(job)
            for parent in settled:
                if parent.on_done is not None:
                    try:
                        parent.on_done(parent)
                    except Exception:   # noqa: BLE001 — observer boundary
                        pass

    def _pool_states(self) -> "list[PoolState]":
        pools: dict[str, PoolState] = {}

        def state(name: str) -> PoolState:
            if name not in pools:
                cfg = self._pool_cfg_cache.get(name) or {}
                pools[name] = PoolState(
                    name=name,
                    weight=float(cfg.get("weight", 1.0)),
                    min_share_ratio=float(cfg.get("min_share_ratio", 0.0)),
                    max_running_jobs=cfg.get("max_running_jobs"))
            return pools[name]

        for job in self._pending:
            state(job.pool).pending += 1
        for job in self._running:
            state(job.pool).running += 1
        result = list(pools.values())
        compute_fair_shares(result, self.slots)
        return result

    def _next_job_locked(self) -> Optional[Job]:
        if not self._pending:
            return None
        pools = self._pool_states()
        chosen = pick_pool(pools)
        if chosen is None:
            return None
        for i, job in enumerate(self._pending):
            if job.pool == chosen.name:
                return self._pending.pop(i)
        return None

    def _worker_loop(self) -> None:
        consecutive_failures = 0
        while True:
            try:
                with self._lock:
                    job = self._next_job_locked()
                    while job is None:
                        if self._stop:
                            return
                        self._lock.wait(timeout=0.5)
                        job = self._next_job_locked()
                    job.state = "running"
                    job.started_at = time.monotonic()
                    self._running.append(job)
                try:
                    ok = self._execute(job)
                except failpoints.InjectedCrash:
                    # Simulated slot death mid-job: requeue the orphan
                    # and let this thread die (the monitor respawns a
                    # replacement) — the worker-death recovery path.
                    self._on_worker_death(job)
                    return
                if ok:
                    consecutive_failures = 0
                else:
                    consecutive_failures += 1
                    if consecutive_failures >= self.slot_ban_after:
                        logger.warning(
                            "job slot banned for %.1fs after %d "
                            "consecutive failures",
                            self.slot_ban_seconds, consecutive_failures)
                        _profiler.counter("slot_banned").increment()
                        consecutive_failures = 0
                        time.sleep(self.slot_ban_seconds)
            except Exception:   # noqa: BLE001 — a slot must never die
                logger.exception("job slot scheduling pass failed")
                time.sleep(0.1)

    def _on_worker_death(self, job: Job) -> None:
        """This slot thread is dying with `job` claimed: hand the job
        back (attempt+1) and drop the thread from the slot roster so
        _ensure_workers spawns a replacement."""
        with self._lock:
            if job in self._running:
                self._running.remove(job)
            if not job._done.is_set() and not job._lost:
                job._proc = None
                job.state = "pending"
                job.attempt += 1
                self._pending.append(job)
            me = threading.current_thread()
            if me in self._workers:
                self._workers.remove(me)
            _profiler.counter("worker_died").increment()
            self._ensure_workers()
            self._lock.notify_all()
        logger.warning("job slot died (injected crash); job %s requeued",
                       job.id)

    # -- execution -------------------------------------------------------------

    def _execute(self, job: Job) -> bool:
        """Run one claimed job to a settled (or requeued) state.  Returns
        False iff the job GENUINELY failed (the run raised and the job
        was not killed on purpose) — the slot's consecutive-failure
        quarantine counts on it, so preemption/speculation-loss/abort
        kills must not read as slot faults.  May raise InjectedCrash
        (worker-death failpoint); the caller owns that recovery."""
        prof = _profiler.with_tags(pool=job.pool)
        prof.counter("started").increment()
        try:
            # worker_death is meaningful as crash-once (InjectedCrash is
            # a BaseException, so it pierces this try); its error mode
            # degrades to an ordinary job failure.
            _FP_WORKER_DEATH.hit()
            _FP_START.hit()
            result = job.run(job)
            _FP_FINISH.hit()
            ok = True
        except YtError as err:
            ok = False
            error = err
        except Exception as exc:      # noqa: BLE001 — job boundary
            ok = False
            error = YtError(f"Job crashed: {exc!r}",
                            code=EErrorCode.OperationFailed)
        duration = time.monotonic() - job.started_at
        with self._lock:
            if job in self._running:
                self._running.remove(job)
            if job._done.is_set():
                # Already settled by a winning speculative twin (result
                # copied, waiters woken) — this unwinding run must not
                # clobber the settled state or re-queue a delivered job.
                job._proc = None
                return True
            job.duration = duration
            if job._preempted:
                # Same object re-queues (waiters hold it); don't signal.
                # A preemption kill is not a slot fault.
                job._preempted = False
                job._proc = None
                job.state = "pending"
                job.attempt += 1
                self._pending.append(job)
                self._lock.notify_all()
                return True
            if job._lost and job.split_children is not None:
                # Killed FOR the split: stays unsettled until the children
                # deliver (the monitor's settle pass owns it now).
                job._proc = None
                return True
            slot_ok = True
            if job._lost:
                job.state = "aborted"   # deliberate kill: not a slot fault
            elif ok:
                job.state = "completed"
                job.result = result
                job.error = None    # a quarantine-absorbed earlier failure
                # must not read as this (completed) job's error upstream.
                if job.record_duration:
                    self._completed_durations.setdefault(
                        job.op_id, []).append(duration)
                self._settle_speculation_locked(job)
            elif job.failures + 1 < job.max_failures:
                # Failure quarantine (ref max_failed_job_count): the
                # failure budget absorbs transient faults; waiters keep
                # their handle and only the LAST failure settles.
                prof.counter("retried").increment()
                job._proc = None
                job.state = "pending"
                job.attempt += 1
                job.failures += 1
                job.error = error
                self._pending.append(job)
                self._lock.notify_all()
                return False
            else:
                job.state = "failed"
                job.failures += 1
                job.error = error
                prof.counter("failed").increment()
                slot_ok = False
            job._done.set()
            self._lock.notify_all()
        if job.on_done is not None:
            try:
                job.on_done(job)
            except Exception:      # noqa: BLE001 — observer boundary
                pass
        return slot_ok

    def _kill(self, job: Job) -> None:
        job._lost = True
        _kill_job_process(job)

    # -- job splitting ---------------------------------------------------------

    def _straggler_threshold(self, op_id: str) -> Optional[float]:
        done = self._completed_durations.get(op_id) or []
        if not done:
            return None
        median = sorted(done)[len(done) // 2]
        return max(median * self.speculation_factor,
                   self.min_speculation_seconds)

    def _split_candidates_locked(self) -> "list[Job]":
        """Stragglers eligible for a split (ref job_splitter.h).  Splitting
        beats speculation when available: the duplicate would repeat ALL
        the work, the split halves it.  The user splitter itself runs
        OUTSIDE the lock (_perform_split)."""
        now = time.monotonic()
        out = []
        for job in list(self._running):
            if job.splitter is None or job.split_children is not None or \
                    job._split_pending or not job.preemptible or \
                    job.speculative_of is not None:
                continue
            if any(s.speculative_of is job
                   for s in self._pending + self._running):
                continue
            threshold = self._straggler_threshold(job.op_id)
            if threshold is None or now - job.started_at < threshold:
                continue
            job._split_pending = True     # blocks speculation meanwhile
            out.append(job)
        return out

    def _perform_split(self, job: Job) -> None:
        try:
            children = job.splitter(job)
        except Exception:   # noqa: BLE001 — splitter is user territory
            logger.exception("job splitter failed for %s", job.id)
            job.splitter = None
            job._split_pending = False
            return
        if len(children) < 2:
            job.splitter = None          # too small; speculation may apply
            job._split_pending = False
            return
        for child in children:
            child.record_duration = False
        with self._lock:
            # The job may have settled while the splitter ran.
            if job._done.is_set() or job not in self._running or \
                    job.split_children is not None:
                return
            logger.info("splitting job %s into %d children",
                        job.id, len(children))
            _profiler.counter("split").increment()
            job.split_children = children
            self._split_parents.append(job)
            self._kill(job)      # unwinds unsettled; children settle it
            self._pending.extend(children)
            self._lock.notify_all()

    def _settle_splits_locked(self) -> "list[Job]":
        """A split parent completes when every child has; the first child
        failure fails the parent.  Returns the settled parents — their
        on_done observers fire OUTSIDE the lock."""
        settled = []
        for parent in list(self._split_parents):
            children = parent.split_children or []
            failed = next((c for c in children if c.state == "failed"),
                          None)
            if failed is not None:
                parent.state = "failed"
                parent.error = failed.error
            elif all(c.state == "completed" for c in children):
                parent.state = "completed"
                result: list = []
                for child in children:
                    result.extend(child.result or [])
                parent.result = result
            else:
                continue
            self._split_parents.remove(parent)
            parent._done.set()
            self._lock.notify_all()
            settled.append(parent)
        return settled

    # -- speculation -----------------------------------------------------------

    def _maybe_speculate_locked(self) -> None:
        """Duplicate stragglers: a preemptible job running far beyond the
        operation's median completed duration gets a twin (first finisher
        wins, ref speculative_job_manager.h)."""
        now = time.monotonic()
        for job in list(self._running):
            if not job.preemptible or job.speculative_of is not None or \
                    job.split_children is not None or job._split_pending:
                continue
            if any(s.speculative_of is job
                   for s in self._pending + self._running):
                continue
            threshold = self._straggler_threshold(job.op_id)
            if threshold is None or now - job.started_at < threshold:
                continue
            twin = Job(op_id=job.op_id, index=job.index, run=job.run,
                       pool=job.pool, preemptible=True,
                       speculative_of=job, on_done=job.on_done)
            twin.attempt = job.attempt + 1
            logger.info("speculating job %s (running %.1fs > %.1fs)",
                        job.id, now - job.started_at, threshold)
            _profiler.counter("speculated").increment()
            self._pending.append(twin)

    def _settle_speculation_locked(self, winner: Job) -> None:
        """First finisher wins; abort the twin."""
        rival = winner.speculative_of
        if rival is not None and not rival._done.is_set():
            # Twin finished first: copy the result onto the original so
            # waiters (which hold the original) observe success.  The
            # logical job's on_done fires exactly once — here via the
            # twin's own _execute; the original's unwinding run takes the
            # settled-state early return BEFORE its callback.
            rival.result = winner.result
            rival.state = "completed"
            rival.duration = winner.duration
            self._kill(rival)
            rival._done.set()
            if rival in self._running:
                self._running.remove(rival)
        for job in self._pending + self._running:
            if job.speculative_of is winner:
                if job in self._pending:
                    self._pending.remove(job)
                    job.state = "aborted"
                    job._done.set()
                else:
                    self._kill(job)

    # -- preemption ------------------------------------------------------------

    def maybe_preempt(self) -> bool:
        """Kill the newest preemptible job of the most-over-share pool when
        another pool is starving; the victim re-queues (attempt + 1).
        Runs automatically from idle workers; public for direct prodding."""
        with self._lock:
            return self._maybe_preempt_locked()

    def _maybe_preempt_locked(self) -> bool:
        pools = self._pool_states()
        victim_pool = find_preemptable(pools)
        if victim_pool is None:
            return False
        victims = [j for j in self._running
                   if j.pool == victim_pool.name and j.preemptible
                   and not j._lost and not j._preempted]
        if not victims:
            return False
        victim = max(victims, key=lambda j: j.started_at)
        logger.info("preempting job %s of pool %s", victim.id, victim.pool)
        _profiler.counter("preempted").increment()
        # The SAME object re-queues when its killed run unwinds (see
        # _execute) — waiters keep their handle.
        victim._preempted = True
        _kill_job_process(victim)
        self._lock.notify_all()
        return True


# -- user-job proxies ----------------------------------------------------------


def _kill_job_process(job: Job) -> None:
    """Kill the job's WHOLE process group: killing only /bin/sh leaves its
    children holding the stdout pipe, and communicate() then blocks until
    they exit on their own.  A job running on an exec node gets a
    best-effort remote abort (its poll loop also self-aborts on the
    _lost/_preempted flags)."""
    import os
    import signal
    proc = job._proc
    if proc is not None and proc.poll() is None:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            try:
                proc.kill()
            except OSError:
                pass
    remote = job._remote
    if remote is not None:
        def _abort(addr=remote[0], rid=remote[1]):
            from ytsaurus_tpu.rpc import Channel
            channel = Channel(addr, timeout=10)
            try:
                channel.call("exec_node", "abort_job", {"job_id": rid})
            except YtError:
                pass
            finally:
                channel.close()
        threading.Thread(target=_abort, daemon=True).start()


def run_remote_command_job(job: Job, address: str, body: dict,
                           input_blob: Optional[bytes] = None,
                           timeout: Optional[float] = None) -> bytes:
    """Dispatch one command job to an exec node and poll to completion;
    returns the job's stdout blob.

    Ref: the scheduler->exec-node allocation + job-proxy supervision
    hop (server/scheduler/node_shard.cpp, server/node/exec_node/job
    controller), collapsed to start/poll/abort RPCs."""
    from ytsaurus_tpu.rpc import Channel, RetryingChannel
    from ytsaurus_tpu.rpc.wire import wire_text as _text
    if job._lost or job._preempted:
        raise YtError("job canceled before start", code=EErrorCode.Canceled)
    # Attempts/backoff come from the process retry policy (config.py
    # "job_rpc"), not per-call-site constants: fail fast so the job
    # revives on another node.
    channel = RetryingChannel(Channel(address, timeout=30),
                              policy="job_rpc")
    remote_id = None
    delivered = False
    # Dedup key: a transport retry of start_job must not double-start
    # the command on the node (ExecNodeService keys running jobs by it).
    body = dict(body)
    body["job_key"] = f"{job.id}:{job.attempt}"
    try:
        res, _ = channel.call(
            "exec_node", "start_job", body,
            attachments=[input_blob] if input_blob is not None else (),
            idempotent=False)
        remote_id = _text(res["job_id"])
        job._remote = (address, remote_id)
        deadline = time.monotonic() + timeout if timeout else None
        interval = 0.1
        while True:
            if job._lost or job._preempted:
                raise YtError("job canceled", code=EErrorCode.Canceled)
            poll, attachments = channel.call(
                "exec_node", "poll_job", {"job_id": remote_id})
            state = _text(poll["state"])
            if state == "completed":
                delivered = True
                return attachments[0]
            if state in ("failed", "aborted"):
                raise YtError(
                    f"remote job failed on {address}: "
                    f"{_text(poll.get('error') or '')}",
                    code=EErrorCode.OperationFailed,
                    attributes={
                        "stderr": _text(poll.get("stderr_tail") or ""),
                        "exit_code": poll.get("exit_code")})
            if deadline is not None and time.monotonic() > deadline:
                raise YtError(f"remote job on {address} timed out",
                              code=EErrorCode.Timeout)
            time.sleep(interval)
            interval = min(interval * 1.6, 1.5)
    finally:
        if remote_id is not None and not delivered:
            # ANY non-success exit (cancel, poll-retry exhaustion, poll
            # timeout) must stop the remote process: the caller may
            # revive the job elsewhere, and an orphan would keep a slot
            # busy and re-run user side effects.
            try:
                channel.call("exec_node", "abort_job",
                             {"job_id": remote_id})
            except YtError:
                pass
        job._remote = None
        channel.close()


def run_command_job(job: Job, command: str, input_blob: bytes,
                    timeout: Optional[float] = None,
                    env: Optional[dict] = None,
                    limits: Optional[dict] = None) -> bytes:
    """Run a user command with formatted rows on stdin; returns stdout.

    Ref: job_proxy user_job.cpp — a separate process (own process group,
    the slot-isolation analog), wire-format pipes, stderr tail kept on
    the job, non-zero exit = job failure.  `limits` applies the job
    environment's resource enforcement (rlimits) in the child — see
    operations/job_environment.py."""
    import os

    from ytsaurus_tpu.operations.job_environment import (
        classify_failure,
        make_preexec,
    )
    if job._lost or job._preempted:
        # Killed before the process spawned: don't start work that is
        # already condemned.
        raise YtError("job canceled before start", code=EErrorCode.Canceled)
    proc = subprocess.Popen(
        ["/bin/sh", "-c", command],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        start_new_session=True,
        preexec_fn=make_preexec(limits),
        env={**os.environ, **(env or {}),
             "YT_JOB_ID": job.id, "YT_JOB_INDEX": str(job.index),
             "YT_OPERATION_ID": job.op_id})
    job._proc = proc
    if job._lost or job._preempted:
        # A kill issued between the check above and _proc assignment saw
        # no process; finish the kill ourselves.
        _kill_job_process(job)
    try:
        stdout, stderr = proc.communicate(input_blob, timeout=timeout)
    except subprocess.TimeoutExpired:
        _kill_job_process(job)
        proc.communicate()
        raise YtError(f"User job {job.id} timed out",
                      code=EErrorCode.Timeout)
    finally:
        job._proc = None
    job.stderr_tail = stderr[-STDERR_TAIL_BYTES:]
    if job._lost:
        raise YtError("job preempted", code=EErrorCode.Canceled)
    if proc.returncode != 0:
        attributes = {"stderr": job.stderr_tail.decode("utf-8",
                                                       "replace"),
                      "exit_code": proc.returncode}
        cause = classify_failure(proc.returncode, job.stderr_tail,
                                 limits)
        if cause:
            attributes["probable_cause"] = cause
        raise YtError(
            f"User job {job.id} failed with exit code {proc.returncode}",
            code=EErrorCode.OperationFailed, attributes=attributes)
    return stdout
