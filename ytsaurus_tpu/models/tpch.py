"""TPC-H-shaped workloads: the framework's flagship "models".

Data generators (seeded, numpy) + query text for the BASELINE.md configs:
  Q1  — scan + filter + 8-aggregate GROUP BY over lineitem
  Q3  — two-table join + GROUP BY (customer/orders condensed into dims)
These drive bench.py and the graft entry.
"""

from __future__ import annotations

import numpy as np

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.schema import TableSchema

LINEITEM_SCHEMA = TableSchema.make([
    ("l_orderkey", "int64"),
    ("l_quantity", "double"),
    ("l_extendedprice", "double"),
    ("l_discount", "double"),
    ("l_tax", "double"),
    ("l_returnflag", "string"),
    ("l_linestatus", "string"),
    ("l_shipdate", "int64"),          # days since epoch
])

ORDERS_SCHEMA = TableSchema.make([
    ("o_orderkey", "int64", "ascending"),
    ("o_custkey", "int64"),
    ("o_orderdate", "int64"),
    ("o_shippriority", "int64"),
])

# TPC-H date constants expressed as days since 1970-01-01.
_DATE_1998_09_02 = 10471
_DATE_1995_03_15 = 9204

Q1 = (
    "l_returnflag, l_linestatus, "
    "sum(l_quantity) AS sum_qty, "
    "sum(l_extendedprice) AS sum_base_price, "
    "sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price, "
    "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge, "
    "avg(l_quantity) AS avg_qty, "
    "avg(l_extendedprice) AS avg_price, "
    "avg(l_discount) AS avg_disc, "
    "count(*) AS count_order "
    f"FROM [//tpch/lineitem] WHERE l_shipdate <= {_DATE_1998_09_02} "
    "GROUP BY l_returnflag, l_linestatus"
)

Q3 = (
    "l_orderkey, "
    "sum(l_extendedprice * (1 - l_discount)) AS revenue "
    "FROM [//tpch/lineitem] "
    "JOIN [//tpch/orders] ON l_orderkey = o_orderkey "
    f"WHERE o_orderdate < {_DATE_1995_03_15} "
    "GROUP BY l_orderkey "
    "ORDER BY sum(l_extendedprice * (1 - l_discount)) DESC, l_orderkey "
    "LIMIT 10"
)


def device_planes(specs: dict, n_rows: int, seed: int = 0) -> dict:
    """Generate column planes ON DEVICE with jax.random — nothing crosses
    the host↔device link (the tunnel moves ~17 MB/s in this environment,
    so host-generated 64M-row tables can never be staged within a bench
    budget; TPU-native benches generate in HBM, the in-memory-mode analog).

    specs: name → ("arange",) | ("randint", lo, hi) | ("uniform", lo, hi)
                 | ("randint_f64", lo, hi)
    Planes come back zero-padded to pad_capacity(n_rows) with values only
    in [0, n_rows).
    """
    import jax
    import jax.numpy as jnp
    from jax import random

    from ytsaurus_tpu.chunks.columnar import pad_capacity

    cap = pad_capacity(max(n_rows, 1))
    names = sorted(specs)

    def gen(key):
        valid = jnp.arange(cap) < n_rows
        out = {}
        for i, name in enumerate(names):
            spec = specs[name]
            k = random.fold_in(key, i)
            kind = spec[0]
            if kind == "arange":
                plane = jnp.arange(cap, dtype=jnp.int64)
            elif kind == "randint":
                plane = random.randint(k, (cap,), spec[1], spec[2],
                                       dtype=jnp.int64)
            elif kind == "randint_f64":
                plane = random.randint(k, (cap,), spec[1], spec[2],
                                       dtype=jnp.int64).astype(jnp.float64)
            elif kind == "uniform":
                plane = random.uniform(k, (cap,), dtype=jnp.float64,
                                       minval=spec[1], maxval=spec[2])
            else:
                raise ValueError(f"Unknown spec {spec!r}")
            zero = jnp.zeros((), dtype=plane.dtype)
            out[name] = jnp.where(valid, plane, zero)
        return out

    return jax.jit(gen)(random.PRNGKey(seed))


def device_chunk(schema: TableSchema, planes: dict, n_rows: int,
                 dictionaries: dict | None = None) -> ColumnarChunk:
    """Wrap device-resident planes into a ColumnarChunk (no host copy)."""
    import jax.numpy as jnp

    from ytsaurus_tpu.chunks.columnar import Column, pad_capacity
    from ytsaurus_tpu.schema import device_dtype

    cap = pad_capacity(max(n_rows, 1))
    valid = jnp.arange(cap) < n_rows
    columns = {}
    for col in schema:
        data = planes[col.name].astype(device_dtype(col.type))
        vocab = None
        if dictionaries is not None and col.name in dictionaries:
            vocab = np.asarray(dictionaries[col.name], dtype=object)
        columns[col.name] = Column(type=col.type, data=data, valid=valid,
                                   dictionary=vocab)
    return ColumnarChunk(schema=schema, row_count=n_rows, columns=columns)


def generate_lineitem_device(n_rows: int, seed: int = 0,
                             n_orders: int | None = None) -> ColumnarChunk:
    """lineitem generated entirely in HBM (same schema/distributions as
    generate_lineitem; dictionary codes for the two flag columns)."""
    n_orders = n_orders or max(n_rows // 4, 1)
    planes = device_planes({
        "l_orderkey": ("randint", 0, n_orders),
        "l_quantity": ("randint_f64", 1, 51),
        "l_extendedprice": ("uniform", 900.0, 105000.0),
        "l_discount": ("uniform", 0.0, 0.10),
        "l_tax": ("uniform", 0.0, 0.08),
        "l_returnflag": ("randint", 0, 3),
        "l_linestatus": ("randint", 0, 2),
        "l_shipdate": ("randint", 8000, 10600),
    }, n_rows, seed)
    flags = np.array([b"A", b"N", b"R"], dtype=object)
    status = np.array([b"F", b"O"], dtype=object)
    return device_chunk(LINEITEM_SCHEMA, planes, n_rows,
                        dictionaries={"l_returnflag": flags,
                                      "l_linestatus": status})


def generate_orders_device(n_orders: int, seed: int = 1) -> ColumnarChunk:
    planes = device_planes({
        "o_orderkey": ("arange",),
        "o_custkey": ("randint", 0, max(n_orders // 10, 1)),
        "o_orderdate": ("randint", 8000, 10600),
        "o_shippriority": ("randint", 0, 2),
    }, n_orders, seed)
    return device_chunk(ORDERS_SCHEMA, planes, n_orders)


def generate_lineitem(n_rows: int, seed: int = 0,
                      n_orders: int | None = None) -> ColumnarChunk:
    rng = np.random.default_rng(seed)
    n_orders = n_orders or max(n_rows // 4, 1)
    flags = np.array([b"A", b"N", b"R"], dtype=object)
    status = np.array([b"F", b"O"], dtype=object)
    return ColumnarChunk.from_arrays(
        LINEITEM_SCHEMA,
        {
            "l_orderkey": rng.integers(0, n_orders, n_rows),
            "l_quantity": rng.integers(1, 51, n_rows).astype(np.float64),
            "l_extendedprice": rng.uniform(900.0, 105000.0, n_rows),
            "l_discount": rng.uniform(0.0, 0.10, n_rows),
            "l_tax": rng.uniform(0.0, 0.08, n_rows),
            "l_returnflag": rng.integers(0, 3, n_rows),
            "l_linestatus": rng.integers(0, 2, n_rows),
            "l_shipdate": rng.integers(8000, 10600, n_rows),
        },
        dictionaries={"l_returnflag": flags, "l_linestatus": status})


def generate_orders(n_orders: int, seed: int = 1) -> ColumnarChunk:
    rng = np.random.default_rng(seed)
    return ColumnarChunk.from_arrays(
        ORDERS_SCHEMA,
        {
            "o_orderkey": np.arange(n_orders),
            "o_custkey": rng.integers(0, max(n_orders // 10, 1), n_orders),
            "o_orderdate": rng.integers(8000, 10600, n_orders),
            "o_shippriority": rng.integers(0, 2, n_orders),
        })


def q1_reference_numpy(chunk: ColumnarChunk) -> dict:
    """Numpy oracle for Q1 (returns {(flag, status): (sum_qty, count)})."""
    n = chunk.row_count
    ship = np.asarray(chunk.column("l_shipdate").data[:n])
    rf = np.asarray(chunk.column("l_returnflag").data[:n])
    ls = np.asarray(chunk.column("l_linestatus").data[:n])
    qty = np.asarray(chunk.column("l_quantity").data[:n])
    mask = ship <= _DATE_1998_09_02
    out = {}
    for f in range(3):
        for s in range(2):
            sel = mask & (rf == f) & (ls == s)
            out[(f, s)] = (float(qty[sel].sum()), int(sel.sum()))
    return out
