"""Whole-plan SPMD execution: the entire distributed query as ONE program.

The stitched rungs of `coordinate_distributed` re-enter Python between
phases — `_finish_shuffled` runs a count program, blocks on a host read
to size the exchange quota, then runs the exchange program; the host
coordinator stitches N per-shard programs with Python glue.  Flare
(arxiv 1703.08219) and the JIT-in-databases survey (arxiv 2311.04692)
both locate the payoff of native compilation in the WHOLE-QUERY unit:
collapsing the interpretive glue between stages, not the operators.
This module is that collapse for the mesh: scan→filter→[partial
aggregate]→shuffle→aggregate/window→order/topk/project lowers as ONE
`jit(shard_map(...))` program over the `'shard'` axis, with
`with_sharding_constraint` pinning the inputs to the partition-rule
registry's placement and in-program collectives (all_to_all routing,
all_gather merge) replacing the Python-stitched exchanges.

Stage placement is driven by a partition-rule registry (the
`match_partition_rules` idiom of SNIPPETS.md [2]: stage-name regex →
PartitionSpec): `scan/<column>`, `filter`, `bottom/*`, `shuffle/*` and
`local/*` stages map onto `P('shard')`; `front`, `order`, `topk`,
`project`, `limit` are replicated (they run over the all_gathered
rowset on every device).  The registry digest folds into the program
cache key, so a placement change can never serve a stale executable.

The data-dependent decision the stitched path syncs for — the exchange
quota — moves from a per-query host read to a CACHED decision: the
fused program runs with a static pow2 quota, computes the true
transfer-matrix maximum on device, and returns it (with an overflow
flag) stacked WITH the result count — one final device→host transfer,
the only host sync in the whole plan.  On overflow the query re-runs
at the demanded quota (a fresh pow2 rung of the same compile-once
ladder) and the settled quota is memoized per plan shape, so steady
serving never syncs mid-plan and never overflows.  Unfusable plans
(joins, WITH TOTALS) and any in-program fault degrade to the stitched
ladder in `coordinate_distributed`.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import replace as dc_replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ytsaurus_tpu.parallel.compat import shard_map

from ytsaurus_tpu.chunks.columnar import pad_capacity
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.parallel.mesh import SHARD_AXIS
from ytsaurus_tpu.parallel.shuffle import route_rows, transfer_counts
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.coordinator import split_plan
from ytsaurus_tpu.query.engine.lowering import prepare
from ytsaurus_tpu.query.parameterize import plan_fingerprint

# -- partition-rule registry ---------------------------------------------------

# Stage-name regex → PartitionSpec (the match_partition_rules idiom,
# SNIPPETS.md [2]).  Sharded stages run inside the shard_map body on the
# per-device slice; replicated stages run after the in-program
# all_gather (every device computes the same merge).  Rules are matched
# first-hit, so a custom registry can pin one stage or column family
# ("scan/l_.*") ahead of the defaults.
DEFAULT_PARTITION_RULES: "tuple[tuple[str, P], ...]" = (
    (r"^(scan|filter|bottom|shuffle|local|join)(/|$)", P(SHARD_AXIS)),
    (r"^(front|merge|order|topk|project|limit)(/|$)", P()),
)


def match_partition_rules(rules, name: str) -> P:
    """First rule whose regex matches `name` wins; no match is an error
    (an unplaceable stage must fail loudly, not silently replicate)."""
    for pattern, spec in rules:
        if re.search(pattern, name) is not None:
            return spec
    raise YtError(f"No partition rule matches stage {name!r}",
                  code=EErrorCode.QueryExecutionError)


def rules_fingerprint(rules) -> str:
    """Stable digest of a rule set — a cache-key axis, so editing the
    registry can never serve a program compiled under the old placement."""
    text = repr([(pattern, tuple(spec)) for pattern, spec in rules])
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def _validate_stages(rules, stages: "list[tuple[str, bool]]") -> None:
    """Check the registry places every stage where the fused program can
    execute it: (name, wants_sharded) pairs."""
    for name, want_sharded in stages:
        spec = match_partition_rules(rules, name)
        sharded = tuple(spec) == (SHARD_AXIS,)
        if sharded != want_sharded:
            where = "on the shard axis" if want_sharded else "replicated"
            raise YtError(
                f"partition rules place stage {name!r} as {tuple(spec)!r} "
                f"but the fused program runs it {where}",
                code=EErrorCode.QueryExecutionError)


# -- fusion gate ---------------------------------------------------------------


def can_fuse(plan: ir.Query) -> Optional[str]:
    """None when the whole plan lowers as one SPMD program; otherwise
    the reason it stays on the stitched ladder.  Multiway equi-join
    plans fuse since ISSUE 14 (planner-ordered broadcast/partition
    joins ride inside the one program — `_run_join`); WITH TOTALS stays
    stitched (it concatenates two materialized rowsets)."""
    if plan.group is not None and plan.group.totals:
        return "WITH TOTALS concatenates two materialized rowsets"
    return None


def _shape_of(plan: ir.Query) -> str:
    """Which fused shape serves this plan:

    exchange-states  GROUP BY without cardinality: partial aggregate
                     states per shard, then the states (not the rows)
                     ride the all_to_all — the in-program combiner.
    exchange-rows    cardinality GROUP BY / windowed plans: complete
                     groups (partitions) need the raw rows co-located.
    gather           everything else: bottom per shard, all_gather,
                     replicated front.
    """
    if plan.group is not None and not plan.group.totals:
        if any(a.function == "cardinality"
               for a in plan.group.aggregate_items):
            return "exchange-rows"
        return "exchange-states"
    if plan.window is not None and plan.window.partition_items:
        return "exchange-rows"
    return "gather"


# -- entry ---------------------------------------------------------------------


def run_whole_plan(evaluator, plan: ir.Query, table, stats=None,
                   rules=None, foreign_chunks=None):
    """Execute `plan` over a ShardedTable as ONE fused SPMD program.

    `evaluator` is the DistributedEvaluator owning the compile ladder
    (memory cache → AOT disk tier → fresh compile) and the quota memo.
    `foreign_chunks` maps join table path → replicated ColumnarChunk
    (multiway join plans fuse through `_run_join`).  Raises YtError for
    unfusable plans or in-program faults — the caller's degradation
    ladder steps down to the stitched rungs.
    """
    reason = can_fuse(plan)
    if reason is not None:
        raise YtError(f"plan is not whole-plan fusable: {reason}",
                      code=EErrorCode.QueryUnsupported)
    rules = DEFAULT_PARTITION_RULES if rules is None else tuple(rules)
    if plan.joins:
        chunk = _run_join(evaluator, plan, table, rules, stats,
                          foreign_chunks or {})
    else:
        shape = _shape_of(plan)
        if shape == "gather":
            chunk = _run_gather(evaluator, plan, table, rules, stats)
        else:
            chunk = _run_exchange(evaluator, plan, table, rules, shape,
                                  stats)
    if stats is not None:
        stats.whole_plan = 1
    return chunk


def _read_counts(final) -> np.ndarray:
    """THE whole-plan host sync: ONE stacked device→host transfer.
    Every fused shape funnels its single blocking read through here —
    gather programs return a bare count, exchange programs a (count,
    overflow, max-cell) triple, fused-join programs the count plus the
    per-join quota-demand/actual telemetry block.  Returns a 1-D int64
    vector; callers index their layout."""
    from ytsaurus_tpu.utils import sanitizers
    sanitizers.note_host_sync("whole_plan._read_counts")
    vals = np.asarray(final)
    if vals.ndim == 0:
        return np.array([int(vals)], dtype=np.int64)
    return vals.astype(np.int64).reshape(-1)


# -- mesh telemetry (ISSUE 20) -------------------------------------------------

# Layout version of the telemetry lanes appended to the stacked final
# transfer.  Rides as the first appended lane so a decoder can never
# misread a layout change as data.
MESH_TELEMETRY_VERSION = 1


def _mesh_armed() -> bool:
    """Whether the in-program mesh telemetry block is stacked onto the
    final transfer (TelemetryConfig.mesh_telemetry).  Folds into every
    whole-plan cache key — arming or disarming compiles a fresh program,
    it never reinterprets an old one's layout."""
    from ytsaurus_tpu.config import telemetry_config
    return bool(telemetry_config().mesh_telemetry)


def _mesh_lanes(row_valid, shard_out):
    """Device-side shape-independent lanes: [version] + per-shard live
    input rows + per-shard output rows.  Each is replicated via
    all_gather (legal under out_specs=P()), so they concatenate onto the
    existing stacked final — same single transfer, zero extra syncs."""
    version = jnp.full((1,), MESH_TELEMETRY_VERSION, dtype=jnp.int64)
    in_rows = jax.lax.all_gather(
        row_valid.sum().astype(jnp.int64), SHARD_AXIS).reshape(-1)
    out_rows = jax.lax.all_gather(
        shard_out.astype(jnp.int64), SHARD_AXIS).reshape(-1)
    return [version, in_rows, out_rows]


def _mesh_slices(vals, base: int, n: int):
    """Decode the shape-independent lanes appended at index `base` of
    the host-read final vector: (in_rows, out_rows, next_offset)."""
    version = int(vals[base])
    if version != MESH_TELEMETRY_VERSION:
        raise YtError(
            f"mesh telemetry version mismatch: program returned "
            f"{version}, host decodes {MESH_TELEMETRY_VERSION}",
            code=EErrorCode.QueryExecutionError)
    in_rows = vals[base + 1: base + 1 + n]
    out_rows = vals[base + 1 + n: base + 1 + 2 * n]
    return in_rows, out_rows, base + 1 + 2 * n


def _row_bytes(rep_columns) -> int:
    """Host-side bytes-per-row estimate of a routed rowset: encoded
    plane itemsize per EValueType (+1 for the validity plane) summed
    over columns.  An estimate for exchange-byte ACCOUNTING (string
    columns ride int32 dict codes on device), never a capacity."""
    from ytsaurus_tpu.schema import EValueType
    sizes = {EValueType.boolean: 1, EValueType.string: 4}
    total = 0
    for rc in rep_columns.values():
        total += sizes.get(rc.type, 8) + 1
    return total


def _mesh_exchange_entry(stage: str, matrix, demand: int, quota: int,
                         row_bytes: int) -> dict:
    """One all_to_all exchange's decoded telemetry: the flattened
    shard-major n*n transfer-count matrix, total rows/bytes moved, and
    quota demand vs granted (headroom = demand/quota utilization)."""
    cells = [int(x) for x in matrix] if matrix is not None else None
    rows = sum(cells) if cells else 0
    return {"stage": stage, "matrix": cells, "rows": rows,
            "bytes": rows * int(row_bytes), "demand": int(demand),
            "quota": int(quota),
            "headroom": round(float(demand) / float(quota), 4)
            if quota else 0.0}


def _mesh_block(n: int, in_rows, out_rows, exchanges, stages=None,
                path: str = "fused") -> dict:
    """The versioned per-program telemetry block every surface consumes
    (QueryStatistics, EXPLAIN ANALYZE, /mesh, `yt mesh top`).  The
    stitched rungs assemble the SAME shape from host values they
    already read (distributed._stitched_mesh_block)."""
    out = [int(x) for x in out_rows]
    total = sum(out)
    mean = total / float(n) if n else 0.0
    skew = (max(out) / mean) if mean > 0 else 1.0
    block = {"version": MESH_TELEMETRY_VERSION, "path": path,
             "shards": int(n),
             "in_rows": [int(x) for x in in_rows],
             "out_rows": out,
             "skew": round(float(skew), 4),
             "exchange_bytes": int(sum(e["bytes"] for e in exchanges)),
             "exchanges": list(exchanges)}
    if stages:
        block["stages"] = list(stages)
    return block


def _publish_mesh(stats, fingerprint: str, key, block: dict) -> None:
    """Fan one decoded telemetry block out to every surface: the query's
    statistics (EXPLAIN ANALYZE), the mesh observatory roll-up +
    /query/mesh sensors, and the ambient trace span (`yt trace` answers
    "which shard was hot").  Pure host bookkeeping over the vector the
    one sanctioned sync already transferred — zero extra syncs."""
    from ytsaurus_tpu.parallel.mesh_observatory import get_mesh_observatory
    from ytsaurus_tpu.utils import tracing
    obs = get_mesh_observatory()
    mem = obs.memory_for(key)
    if mem is not None:
        block["memory_watermark_bytes"] = mem
    if stats is not None:
        stats.note_mesh_block(block)
    obs.record_execution(fingerprint, block)
    span = tracing.current_trace()
    if span is not None and span.sampled:
        out_rows = block.get("out_rows") or []
        span.add_tag("mesh_skew", block.get("skew"))
        span.add_tag("mesh_exchange_bytes",
                     block.get("exchange_bytes", 0))
        if out_rows:
            hot = int(max(range(len(out_rows)),
                          key=out_rows.__getitem__))
            span.add_tag("mesh_hot_shard", hot)
            span.add_tag("mesh_hot_shard_rows", int(out_rows[hot]))
        if block.get("memory_watermark_bytes"):
            span.add_tag("mesh_memory_watermark_bytes",
                         block["memory_watermark_bytes"])


def _scan_shardings(rules, mesh, names: "list[str]"):
    """NamedShardings for the input planes per the registry ("scan/<col>"
    rules must keep scan columns on the shard axis — the planes ARE
    sharded)."""
    shardings = {}
    stages = []
    for name in names:
        stage = f"scan/{name}"
        stages.append((stage, True))
        shardings[name] = NamedSharding(mesh,
                                        match_partition_rules(rules, stage))
    _validate_stages(rules, stages)
    return shardings


def _constrain_inputs(mesh, shardings, columns: dict, row_valid):
    """`with_sharding_constraint` at the jit boundary: pins the scan
    planes to the registry's placement before the shard_map body (the
    GSPMD spelling of "this stage lives on the shard axis")."""
    out = {}
    for name, (data, valid) in columns.items():
        sh = shardings[name]
        out[name] = (jax.lax.with_sharding_constraint(data, sh),
                     jax.lax.with_sharding_constraint(valid, sh))
    rv = jax.lax.with_sharding_constraint(
        row_valid, NamedSharding(mesh, P(SHARD_AXIS)))
    return out, rv


def _gathered(planes_with_cols, shard_mask, out_cap: int):
    """In-program all_gather of a stage's output planes + mask."""
    gathered = {}
    for out_col, (d, v) in planes_with_cols:
        # Collapse only the (shards, rows) leading axes: trailing dims
        # (vector planes are (rows, dim)) ride through the gather.
        gathered[out_col.name] = (
            jax.lax.all_gather(d, SHARD_AXIS).reshape((-1,) + d.shape[1:]),
            jax.lax.all_gather(v, SHARD_AXIS).reshape(-1))
    g_mask = jax.lax.all_gather(shard_mask, SHARD_AXIS).reshape(-1)
    return gathered, g_mask


# -- gather shape --------------------------------------------------------------


def _run_gather(evaluator, plan: ir.Query, table, rules, stats=None):
    """bottom per shard → all_gather → replicated front, fused.  The
    same dataflow as the stitched gather rung, but compiled through the
    whole-plan ladder (AOT-serializable, registry-placed)."""
    from ytsaurus_tpu.parallel import distributed as dist
    dist._FP_GATHER.hit()
    mesh = table.mesh
    n = mesh.devices.size
    cap = table.capacity
    armed = _mesh_armed()
    bottom, front = split_plan(plan)
    prepared_b = prepare(bottom, table.rep_chunk())
    inter_rep = dist._RepChunk(
        capacity=n * prepared_b.out_capacity,
        columns={c.name: dist._RepColumn(type=c.type, dictionary=c.vocab)
                 for c in prepared_b.output})
    prepared_f = prepare(front, inter_rep)
    names = [c.name for c in bottom.schema if c.name in table.columns]
    shardings = _scan_shardings(rules, mesh, names)
    stages = [("bottom", True), ("front", False)]
    if plan.order is not None:
        stages.append(("order", False))
    if plan.project is not None:
        stages.append(("project", False))
    _validate_stages(rules, stages)
    out_cap = prepared_b.out_capacity

    def build():
        def fused(columns, row_valid, b_bnd, f_bnd):
            planes, count = prepared_b.run(columns, row_valid, b_bnd)
            shard_mask = jnp.arange(out_cap) < count
            gathered, g_mask = _gathered(
                list(zip(prepared_b.output, planes)), shard_mask, out_cap)
            out_planes, out_count = prepared_f.run(gathered, g_mask,
                                                   f_bnd)
            if not armed:
                return out_planes, out_count
            final = jnp.concatenate(
                [out_count.astype(jnp.int64).reshape(1)]
                + _mesh_lanes(row_valid, count))
            return out_planes, final

        mapped = shard_map(
            fused, mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
            out_specs=P(), check_vma=False)

        def program(columns, row_valid, b_bnd, f_bnd):
            columns, row_valid = _constrain_inputs(mesh, shardings,
                                                   columns, row_valid)
            return mapped(columns, row_valid, b_bnd, f_bnd)

        return program

    key = ("whole", "gather", plan_fingerprint(bottom),
           plan_fingerprint(front), n, cap,
           prepared_b.binding_shapes(), prepared_f.binding_shapes(),
           rules_fingerprint(rules), armed)
    columns = {name: (table.columns[name].data, table.columns[name].valid)
               for name in names}
    out_planes, out_count = evaluator._dispatch_spmd(
        key, build, (columns, table.row_valid,
                     tuple(prepared_b.bindings),
                     tuple(prepared_f.bindings)))
    dist._note_host_sync()            # the final count read
    vals = _read_counts(out_count)
    count = int(vals[0])
    if armed:
        in_rows, out_rows, _off = _mesh_slices(vals, 1, n)
        _publish_mesh(stats, plan_fingerprint(plan), key,
                      _mesh_block(n, in_rows, out_rows, exchanges=[]))
    return dist._assemble_chunk(prepared_f.output, out_planes, count)


# -- exchange shapes -----------------------------------------------------------


def _bind_route_keys(rep_columns, key_refs, where_expr):
    """Bind routing-key expressions (+ optional WHERE) against a
    namespace of _RepColumn-like carriers.  Returns (bind_ctx, where_b,
    key_b)."""
    from ytsaurus_tpu.query.engine.expr import BindContext, ColumnBinding, \
        ExprBinder
    bind_ctx = BindContext(columns={
        name: ColumnBinding(type=rc.type, vocab=rc.dictionary)
        for name, rc in rep_columns.items()})
    binder = ExprBinder(bind_ctx)
    where_b = binder.bind(where_expr) if where_expr is not None else None
    key_b = [binder.bind(expr) for expr in key_refs]
    return bind_ctx, where_b, key_b


def _dest_hash(key_b, ctx, mask, cap: int, n: int):
    """Destination device by canonical key hash (mirrors the stitched
    shuffle's routing so both paths co-locate identical key sets)."""
    from ytsaurus_tpu.query.engine.expr import _combine_u64, _mix_u64
    from ytsaurus_tpu.parallel.distributed import _canonical_hash_plane
    acc = jnp.full(cap, np.uint64(0x9E3779B97F4A7C15), dtype=jnp.uint64)
    for kb in key_b:
        data, valid = kb.emit(ctx)
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int8)
        h = _mix_u64(_canonical_hash_plane(data))
        h = jnp.where(valid, h, jnp.zeros_like(h))
        acc = _combine_u64(acc, h)
    pid = (acc % np.uint64(n)).astype(jnp.int32)
    return jnp.where(mask, pid, n)


def _initial_quota(memo: dict, memo_key, bound_cap: int, n: int,
                   headroom: float) -> "tuple[int, int]":
    """(starting quota, hard bound).  The bound is the per-source live
    capacity — a source cannot send more rows than it holds to one
    destination, so a program at the bound can never overflow."""
    bound = pad_capacity(bound_cap)
    start = memo.get(memo_key)
    if start is None:
        start = min(bound,
                    pad_capacity(max(64, int(bound_cap * headroom) // n)))
    return start, bound


def _settle_quota(memo: dict, memo_key, demand: int,
                  bound: int) -> None:
    """Memoize the demand-sized quota for the next query of this shape.
    pow2 rounding of the MEASURED demand is the steady-state slack
    (multiplying by the configured headroom first would double most
    capacities for nothing — headroom belongs to the overflow
    escalation, where the estimate has proven short).  Hysteresis: only
    shrink past a 4x gap, and upward moves always apply, so per-query
    demand jitter cannot thrash the compile cache with alternating
    quota rungs."""
    settled = min(bound, pad_capacity(max(int(demand), 64)))
    prev = memo.get(memo_key)
    if prev is None or settled > prev or settled * 4 <= prev:
        memo[memo_key] = settled


def _run_exchange(evaluator, plan: ir.Query, table, rules, shape: str,
                  stats):
    """The co-partitioned shapes, fused end to end:

    exchange-states  scan→filter→partial group (per shard) → all_to_all
                     of the GROUP STATES by key hash → merge group +
                     having (complete groups per device) → all_gather →
                     order/project/offset/limit.  The exchange moves
                     aggregate states, not rows — the in-program
                     combiner.
    exchange-rows    scan→filter → all_to_all of the surviving ROWS by
                     group/PARTITION BY hash → full local stage
                     (complete groups: cardinality; complete partitions:
                     window) → all_gather → front.

    One static pow2 quota sizes the exchange; the program returns the
    true transfer max + overflow flag WITH the count (one stacked final
    transfer).  Overflow re-runs at the demanded quota and memoizes it.
    """
    from ytsaurus_tpu.config import compile_config
    from ytsaurus_tpu.parallel import distributed as dist
    from ytsaurus_tpu.query.engine.expr import EmitContext

    dist._FP_ALL_TO_ALL.hit()
    mesh = table.mesh
    n = mesh.devices.size
    cap = table.capacity
    headroom = compile_config().whole_plan_headroom
    armed = _mesh_armed()

    if shape == "exchange-states":
        bottom, front = split_plan(plan)
        prepared_s1 = prepare(bottom, table.rep_chunk())
        bound_cap = prepared_s1.out_capacity
        route_rep = {c.name: dist._RepColumn(type=c.type, dictionary=c.vocab)
                     for c in prepared_s1.output}
        route_names = [c.name for c in prepared_s1.output]
        # Routing keys: the group-key slots of the state rowset (bare
        # references — the bottom already evaluated the expressions).
        key_refs = [ir.TReference(type=item.expr.type, name=item.name)
                    for item in bottom.group.group_items]
        where_expr = None                 # consumed by the bottom
        local_plan = ir.FrontQuery(schema=front.schema, group=front.group,
                                   having=front.having)
        front_final = ir.FrontQuery(
            schema=local_plan.output_schema(), order=front.order,
            project=front.project, offset=front.offset, limit=front.limit)
        stage_names = [("bottom/group", True), ("shuffle/group", True),
                       ("local/group", True), ("front", False)]
    else:
        bottom = None
        prepared_s1 = None
        bound_cap = cap
        route_rep = {name: dist._RepColumn(type=col.type,
                                           dictionary=col.dictionary)
                     for name, col in table.columns.items()}
        route_names = [c.name for c in plan.schema
                       if c.name in table.columns]
        route_rep = {name: route_rep[name] for name in route_names}
        key_items = plan.window.partition_items \
            if plan.window is not None else plan.group.group_items
        key_refs = [item.expr for item in key_items]
        where_expr = plan.where
        local_plan = dc_replace(plan, order=None, project=None, offset=0,
                                limit=None)
        front_final = None                # built per quota below
        kind = "window" if plan.window is not None else "group"
        stage_names = [(f"shuffle/{kind}", True), (f"local/{kind}", True),
                       ("front", False)]
    if plan.order is not None:
        stage_names.append(("order", False))
    if plan.project is not None:
        stage_names.append(("project", False))
    _validate_stages(rules, stage_names)

    key_ctx, where_b, key_b = _bind_route_keys(route_rep, key_refs,
                                               where_expr)
    key_bindings = tuple(key_ctx.bindings)
    if shape == "exchange-states":
        columns = {name: (table.columns[name].data,
                          table.columns[name].valid)
                   for name in [c.name for c in bottom.schema
                                if c.name in table.columns]}
        scan_names = sorted(columns)
    else:
        columns = {name: (table.columns[name].data,
                          table.columns[name].valid)
                   for name in route_names}
        scan_names = route_names
    shardings = _scan_shardings(rules, mesh, scan_names)

    memo_key = (shape, plan_fingerprint(plan), n, bound_cap)
    quota, bound = _initial_quota(evaluator._quota_memo, memo_key,
                                  bound_cap, n, headroom)

    while True:
        recv_cap = n * quota
        local_rep = dist._RepChunk(
            capacity=recv_cap, columns=dict(route_rep))
        prepared_local = prepare(local_plan, local_rep)
        out_cap = prepared_local.out_capacity
        if shape == "exchange-states":
            final_plan = front_final
        else:
            final_plan = ir.FrontQuery(
                schema=local_plan.output_schema(), order=plan.order,
                project=plan.project, offset=plan.offset,
                limit=plan.limit)
        front_rep = dist._RepChunk(
            capacity=n * out_cap,
            columns={c.name: dist._RepColumn(type=c.type,
                                             dictionary=c.vocab)
                     for c in prepared_local.output})
        prepared_front = prepare(final_plan, front_rep)

        def build(quota=quota, prepared_local=prepared_local,
                  prepared_front=prepared_front, out_cap=out_cap):
            def fused(columns, row_valid, s1_bnd, key_bnd, l_bnd, f_bnd):
                if prepared_s1 is not None:
                    planes, cnt = prepared_s1.run(columns, row_valid,
                                                  s1_bnd)
                    routed = {c.name: plane for c, plane in
                              zip(prepared_s1.output, planes)}
                    mask = jnp.arange(bound_cap) < cnt
                else:
                    routed = {name: columns[name] for name in route_names}
                    mask = row_valid
                ctx = EmitContext(columns=routed, bindings=key_bnd,
                                  capacity=bound_cap)
                if where_b is not None:
                    d, v = where_b.emit(ctx)
                    mask = mask & v & d.astype(bool)
                pid = _dest_hash(key_b, ctx, mask, bound_cap, n)
                cell_counts = transfer_counts(pid, mask, n)
                recv, recv_mask = route_rows(routed, pid, n, quota,
                                             bound_cap)
                planes2, cnt2 = prepared_local.run(recv, recv_mask,
                                                   l_bnd)
                shard_mask = jnp.arange(out_cap) < cnt2
                gathered, g_mask = _gathered(
                    list(zip(prepared_local.output, planes2)),
                    shard_mask, out_cap)
                out_planes, out_count = prepared_front.run(gathered,
                                                           g_mask, f_bnd)
                # Replicated exchange telemetry riding the result: the
                # true transfer-matrix max (quota demand) + overflow.
                all_cells = jax.lax.all_gather(
                    cell_counts, SHARD_AXIS).reshape(-1)
                max_cell = all_cells.max().astype(jnp.int64)
                over = (max_cell > quota).astype(jnp.int64)
                final = jnp.stack(
                    [out_count.astype(jnp.int64), over, max_cell])
                if armed:
                    # Mesh telemetry lanes (ISSUE 20) append AFTER the
                    # existing layout — same stacked transfer.
                    final = jnp.concatenate(
                        [final] + _mesh_lanes(row_valid, cnt2)
                        + [all_cells.astype(jnp.int64)])
                return out_planes, final

            mapped = shard_map(
                fused, mesh=mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P(), P(),
                          P()),
                out_specs=P(), check_vma=False)

            def program(columns, row_valid, s1_bnd, key_bnd, l_bnd,
                        f_bnd):
                columns, row_valid = _constrain_inputs(
                    mesh, shardings, columns, row_valid)
                return mapped(columns, row_valid, s1_bnd, key_bnd,
                              l_bnd, f_bnd)

            return program

        key = ("whole", shape, plan_fingerprint(plan), n, cap, quota,
               bound_cap,
               prepared_s1.binding_shapes() if prepared_s1 is not None
               else None,
               tuple(key_ctx.structure),
               tuple((tuple(b.shape), str(b.dtype))
                     for b in key_bindings),
               prepared_local.binding_shapes(),
               prepared_front.binding_shapes(),
               rules_fingerprint(rules), armed)
        args = (columns, table.row_valid,
                tuple(prepared_s1.bindings) if prepared_s1 is not None
                else (),
                key_bindings, tuple(prepared_local.bindings),
                tuple(prepared_front.bindings))
        out_planes, final = evaluator._dispatch_spmd(key, build, args)
        # Noted PER read: an overflow retry performs a real second
        # stacked transfer and the counter must say so (steady state
        # stays at exactly one).
        dist._note_host_sync()
        vals = _read_counts(final)
        count, over, demand = int(vals[0]), int(vals[1]), int(vals[2])
        if not over:
            break
        if quota >= bound:
            raise YtError(
                "whole-plan exchange overflowed at the maximal quota "
                f"(quota={quota}, demand={demand})",
                code=EErrorCode.QueryExecutionError)
        if stats is not None:
            stats.whole_plan_retries += 1
        quota = min(bound,
                    max(pad_capacity(max(int(demand * headroom), 1)),
                        quota * 2))
    _settle_quota(evaluator._quota_memo, memo_key, demand, bound)
    if armed:
        in_rows, out_rows, off = _mesh_slices(vals, 3, n)
        entry = _mesh_exchange_entry(
            f"shuffle/{shape}", vals[off: off + n * n], demand, quota,
            _row_bytes(route_rep))
        _publish_mesh(stats, plan_fingerprint(plan), key,
                      _mesh_block(n, in_rows, out_rows, [entry]))
    return dist._assemble_chunk(prepared_front.output, out_planes, count)


# -- fused multiway join (ISSUE 14) --------------------------------------------


_OUT_CAP_UNBOUNDED = 1 << 40      # join expansion has no per-source bound


def _join_flat_names(join: ir.Query, needed) -> "list[tuple[str, str]]":
    """(flat output name, foreign column) pairs this join pulls, pruned
    to what the plan reads."""
    pairs = [(f"{join.alias}.{f}" if join.alias else f, f)
             for f in join.foreign_columns]
    if needed is not None:
        pairs = [(flat, f) for flat, f in pairs if flat in needed]
    return pairs


def _gate_fusable_join(join, foreign) -> None:
    """Foreign sides with host-resident payloads (`any` columns) cannot
    ride a device program — degrade to the stitched/host rungs."""
    from ytsaurus_tpu.schema import EValueType
    for fname in join.foreign_columns:
        fcol = foreign.columns.get(fname)
        if fcol is None:
            raise YtError(f"Join table {join.foreign_table!r} has no "
                          f"column {fname!r}",
                          code=EErrorCode.QueryExecutionError)
        if fcol.type is EValueType.any or fcol.host_values is not None:
            raise YtError(
                f"join column {fname!r} carries host payloads — "
                "not whole-plan fusable",
                code=EErrorCode.QueryUnsupported)


def _fallback_decisions(plan_x: ir.Query, foreign_chunks) -> tuple:
    """Planner-off decisions: declared order, broadcast only for small
    sides (same threshold), no pushdown."""
    from ytsaurus_tpu.config import compile_config
    from ytsaurus_tpu.query.planner import JoinDecision
    cap = compile_config().broadcast_join_rows
    out = []
    for i, join in enumerate(plan_x.joins):
        foreign = foreign_chunks.get(join.foreign_table)
        f_rows = foreign.row_count if foreign is not None else 0
        out.append(JoinDecision(
            index=i, strategy="broadcast" if 0 < f_rows <= cap
            else "partition", est_in=0, est_out=0, foreign_rows=f_rows))
    return tuple(out)


class _BroadcastSetup:
    """Replicated probe: sorted foreign key planes + pulled columns ride
    as P() args; per-shard lexicographic search, no exchange."""

    def __init__(self, join, self_bound, self_slots, n_keys,
                 arg_slice, f_cap, flat_names):
        self.join = join
        self.self_bound = self_bound
        self.self_slots = self_slots
        self.n_keys = n_keys
        self.arg_slice = arg_slice
        self.f_cap = f_cap
        self.flat_names = flat_names
        self.strategy = "broadcast"


class _PartitionSetup:
    """Co-partition exchange: both sides route by key hash over the
    in-program all_to_all, then probe + expand per device."""

    def __init__(self, join, self_bound, self_slots, f_bound,
                 foreign_slots, f_shard_index, f_slice, f_count,
                 flat_names):
        self.join = join
        self.self_bound = self_bound
        self.self_slots = self_slots
        self.f_bound = f_bound
        self.foreign_slots = foreign_slots
        self.f_shard_index = f_shard_index
        self.f_slice = f_slice
        self.f_count = f_count
        self.flat_names = flat_names
        self.strategy = "partition"


def _stage_foreign_shards(evaluator, foreign, f_names, n, mesh):
    """Shard a foreign chunk 1/n per device (the partition-join staging
    of the stitched path), memoized per (chunk identity, mesh shape):
    repeated queries against an unchanged dimension table must not
    re-transfer it."""
    from ytsaurus_tpu.chunks.columnar import pad_capacity as _pad
    from ytsaurus_tpu.parallel import distributed as dist
    f_count = foreign.row_count
    f_slice = _pad(max((f_count + n - 1) // n, 1))
    key = ("join-fshard", id(foreign), n, f_slice, tuple(f_names))

    def build():
        shard_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        f_total = n * f_slice
        pad = f_total - f_count
        f_global = {}
        for fname in f_names:
            fcol = foreign.columns[fname]
            data = jnp.concatenate(
                [fcol.data[:f_count],
                 jnp.zeros(pad, dtype=fcol.data.dtype)])
            valid = jnp.concatenate(
                [fcol.valid[:f_count], jnp.zeros(pad, dtype=bool)])
            f_global[fname] = (jax.device_put(data, shard_sharding),
                               jax.device_put(valid, shard_sharding))
        f_row_valid = jax.device_put(jnp.arange(f_total) < f_count,
                                     shard_sharding)
        return f_global, f_row_valid, f_slice

    return dist._chunk_memo(evaluator._cache, key, foreign, build)


def _broadcast_args(evaluator, join, foreign, f_order, f_sorted,
                    flat_names):
    """Replicated probe args for one broadcast join (sorted key planes,
    f_order-gathered pulled columns, live count), memoized with the
    host-order phase's identity discipline."""
    from ytsaurus_tpu.parallel import distributed as dist
    key = ("join-bargs", id(foreign), id(f_order),
           tuple(f for _flat, f in flat_names))

    def build():
        args: list = []
        for v, d in f_sorted:
            args.append(v)
            args.append(d)
        for _flat, fname in flat_names:
            fcol = foreign.columns[fname]
            args.append(fcol.data[f_order])
            args.append(fcol.valid[f_order])
        args.append(jnp.asarray(foreign.row_count, dtype=jnp.int64))
        return tuple(args)

    return dist._chunk_memo(evaluator._cache, key, foreign, build)


def _join_pid(keys, mask, n: int, keep_null_local: bool):
    """Destination device by encoded-key hash (the partitioned-join
    routing of distributed.py): null-keyed live rows stay local for
    LEFT joins (they still emit an unmatched row) and are discarded
    otherwise."""
    from ytsaurus_tpu.query.engine.expr import _combine_u64, _mix_u64
    from ytsaurus_tpu.parallel.distributed import _canonical_hash_plane
    from ytsaurus_tpu.query.engine.joins import null_key_mask
    acc = jnp.full(mask.shape, np.uint64(0x9E3779B97F4A7C15),
                   dtype=jnp.uint64)
    for v, d in keys:
        h = _mix_u64(_canonical_hash_plane(d))
        h = jnp.where(v > 0, h, jnp.zeros_like(h))
        acc = _combine_u64(acc, h)
    pid = (acc % np.uint64(n)).astype(jnp.int32)
    null = null_key_mask(keys)
    if keep_null_local:
        me = jax.lax.axis_index(SHARD_AXIS).astype(jnp.int32)
        pid = jnp.where(null, me, pid)
    else:
        pid = jnp.where(null, n, pid)
    return jnp.where(mask, pid, n)


def _run_join(evaluator, plan: ir.Query, table, rules, stats,
              foreign_chunks: dict):
    """Multiway equi-join plans as ONE fused SPMD program (the ISSUE 14
    tentpole): the cost-based planner (query/planner.py) orders the
    joins and picks broadcast-vs-partition per side off chunk-stats
    cardinalities; broadcast sides replicate their sorted key planes
    (the joins.py lexicographic-search backbone probes them per shard),
    partition sides co-partition BOTH inputs by join-key hash through
    the same in-program all_to_all the GROUP BY shapes use; the joined
    rowset then runs bottom → all_gather → front without leaving the
    program.  The PR 10 memoized quota/overflow protocol covers every
    data-dependent capacity (two exchange quotas + the match-expansion
    output capacity per partition join): static pow2 sizes, true
    demands computed on device and returned stacked WITH the final
    count — one host sync — and an overflow re-runs at the demanded
    rung then memoizes it.  Planner decisions (order, strategies,
    pushdown columns) fold into the program cache key, so a stats-
    driven plan change can never serve a stale program."""
    from dataclasses import replace as dc_replace

    from ytsaurus_tpu.config import compile_config
    from ytsaurus_tpu.parallel import distributed as dist
    from ytsaurus_tpu.query import planner
    from ytsaurus_tpu.query.engine.expr import (
        BindContext, ColumnBinding, EmitContext, ExprBinder,
    )
    from ytsaurus_tpu.query.engine.joins import (
        _bind_keys, _emit_encoded_keys, _lex_searchsorted, null_key_mask,
        probe_replicated, sort_foreign_keys,
    )
    from ytsaurus_tpu.schema import EValueType, TableSchema

    mesh = table.mesh
    n = mesh.devices.size
    cap = table.capacity
    headroom = compile_config().whole_plan_headroom
    armed = _mesh_armed()

    # -- plan: order + strategies + pushdown off the chunk stats -------
    jplan = planner.plan_for_chunks(plan, table.total_rows,
                                    foreign_chunks)
    plan_x = planner.apply_order(plan, jplan)
    decisions = jplan.decisions if jplan is not None else \
        _fallback_decisions(plan_x, foreign_chunks)
    needed = ir.referenced_columns(plan_x)
    scan_names = sorted(name for name in table.columns
                        if needed is None or name in needed)

    # -- host phase: bind every join against the widening namespace ----
    bindings: list = []
    bind_structure: list = []
    namespace: dict = {
        name: ColumnBinding(type=col.type, vocab=col.dictionary)
        for name, col in table.columns.items()}
    rep_columns: dict = {
        name: dist._RepColumn(type=col.type, dictionary=col.dictionary)
        for name, col in table.columns.items()}
    setups: list = []
    rep_args: list = []             # replicated broadcast-probe args
    f_shards: list = []             # per-partition-join sharded planes
    fingerprint_parts: list = []
    # Host-side rowset-width tracking for exchange-byte accounting
    # (ISSUE 20): the self-side routed width at each partition stage is
    # the scan columns + every flat a PRIOR join pulled.
    cur_rep = {name: rep_columns[name] for name in scan_names}
    stage_row_bytes: list = []      # (self, foreign) bytes/row, or None
    for join, decision in zip(plan_x.joins, decisions):
        foreign = foreign_chunks.get(join.foreign_table)
        if foreign is None:
            raise YtError(
                f"No data provided for join table {join.foreign_table!r}",
                code=EErrorCode.QueryExecutionError)
        _gate_fusable_join(join, foreign)
        bind_ctx = BindContext(columns=dict(namespace),
                               bindings=bindings,
                               structure=bind_structure)
        binder = ExprBinder(bind_ctx)
        self_bound = [binder.bind(e) for e in join.self_equations]
        f_bound = _bind_keys(foreign, join.foreign_schema,
                             join.foreign_equations, bindings,
                             structure=bind_structure)
        self_slots, foreign_slots = dist._vocab_remap_slots(
            self_bound, f_bound, bindings)
        flat_names = _join_flat_names(join, needed)
        strategy = decision.strategy
        if strategy == "broadcast":
            # Broadcast needs provably unique foreign keys (the probe
            # gathers a single match row); the host-order phase verifies
            # and memoizes per chunk — non-unique sides fall back to the
            # partition exchange, and the RESOLVED strategy keys caches.
            f_order, f_sorted, unique = dist._foreign_host_order(
                evaluator._cache, join, foreign, self_bound, f_bound,
                foreign_slots, bindings)
            if not unique:
                strategy = "partition"
        if strategy == "broadcast":
            a0 = len(rep_args)
            rep_args.extend(_broadcast_args(evaluator, join, foreign,
                                            f_order, f_sorted,
                                            flat_names))
            setups.append(_BroadcastSetup(
                join, self_bound, self_slots, len(f_bound),
                (a0, len(rep_args)), foreign.capacity, flat_names))
            stage_row_bytes.append(None)
            fingerprint_parts.append(
                ("broadcast", foreign.capacity, foreign.row_count > 0))
        else:
            f_key_refs: set = set()
            for eq in join.foreign_equations:
                f_key_refs.update(ir.expr_references(eq))
            f_names = sorted(f_key_refs | {f for _flat, f in flat_names})
            f_global, f_row_valid, f_slice = _stage_foreign_shards(
                evaluator, foreign, f_names, n, mesh)
            f_shards.append((f_global, f_row_valid))
            setups.append(_PartitionSetup(
                join, self_bound, self_slots, f_bound, foreign_slots,
                len(f_shards) - 1, f_slice, foreign.row_count,
                flat_names))
            stage_row_bytes.append((
                _row_bytes(cur_rep),
                _row_bytes({f: dist._RepColumn(
                    type=foreign.columns[f].type,
                    dictionary=foreign.columns[f].dictionary)
                    for f in f_names})))
            fingerprint_parts.append(
                ("partition", f_slice, foreign.row_count > 0))
        for flat, fname in flat_names:
            fcol = foreign.columns[fname]
            namespace[flat] = ColumnBinding(type=fcol.type,
                                            vocab=fcol.dictionary)
            rep_columns[flat] = dist._RepColumn(type=fcol.type,
                                                dictionary=fcol.dictionary)
            cur_rep[flat] = rep_columns[flat]
        fingerprint_parts.append(tuple(
            len(b.vocab) if b.vocab is not None else -1
            for b in list(self_bound) + list(f_bound)))

    # Semi-join pushdown: selective INNER sides' key ranges mask self
    # rows BEFORE the first exchange (values ride 0-d bindings so stats
    # drift that moves a bound recompiles nothing; the pushed COLUMN set
    # is a planner decision and folds into the key via the token).
    push_slots: list = []
    if jplan is not None:
        pushable = {EValueType.int64, EValueType.uint64, EValueType.double}
        for name, lo, hi in jplan.pushdown_ranges():
            col = table.columns.get(name)
            if col is None or col.type not in pushable:
                continue
            dt = col.data.dtype
            lo_slot = len(bindings)
            bindings.append(jnp.asarray(lo, dtype=dt))
            hi_slot = len(bindings)
            bindings.append(jnp.asarray(hi, dtype=dt))
            push_slots.append((name, lo_slot, hi_slot))
    join_bindings = tuple(bindings)

    # Shuffle-boundary fault sites (the chaos-soak contract): the fused
    # join program ends in an all_gather, and partition joins ride the
    # in-program all_to_all — an injected collective fault knocks this
    # rung out and the ladder serves the query stitched.
    dist._FP_GATHER.hit()
    if any(s.strategy == "partition" for s in setups):
        dist._FP_ALL_TO_ALL.hit()

    columns = {name: (table.columns[name].data, table.columns[name].valid)
               for name in scan_names}
    shardings = _scan_shardings(rules, mesh, scan_names)
    stage_names = [(f"join/{i}", True) for i in range(len(setups))]
    stage_names += [(f"shuffle/join/{i}", True)
                    for i, s in enumerate(setups)
                    if s.strategy == "partition"]
    stage_names += [("bottom", True), ("front", False)]
    _validate_stages(rules, stage_names)

    # -- the post-join plan (bottom per device, all_gather, front) -----
    plan_nojoin = dc_replace(plan_x, joins=())
    if needed is not None:
        plan_nojoin = dc_replace(plan_nojoin, schema=TableSchema(
            columns=tuple(c for c in plan_x.schema if c.name in needed)))
    bottom, front = split_plan(plan_nojoin)

    token = tuple((d.index, s.strategy) for d, s in zip(decisions, setups)) \
        + (tuple(name for name, _lo, _hi in push_slots),)
    memo_base = ("join", plan_fingerprint(plan_x), token, n, cap)

    def initial(kind: str, j: int, est: int, bound: int) -> int:
        memo_key = memo_base + (j, kind)
        start = evaluator._quota_memo.get(memo_key)
        if start is None:
            # pow2 rounding IS the first-guess headroom (1-2x slack):
            # multiplying an accurate estimate by the configured
            # headroom BEFORE rounding doubles every capacity — and the
            # out capacity sizes all post-join stages.  A rare slight
            # under-estimate costs one overflow retry (which applies
            # the headroom) and memoizes; an accurate one runs tight.
            start = min(bound, pad_capacity(max(64, est)))
        return min(start, bound)

    quotas: dict = {}
    for j, (setup, decision) in enumerate(zip(setups, decisions)):
        if setup.strategy != "partition":
            continue
        est_in = max(decision.est_in, 1)
        est_out = max(decision.est_out, 1)
        quotas[j] = {
            # Expected max transfer cell ~ rows-per-device / n under
            # uniform hashing; the overflow protocol absorbs skew.
            "qs": initial("qs", j, est_in // (n * n), cap),
            "qf": initial("qf", j, max(setup.f_count, 1) // (n * n),
                          setup.f_slice),
            "out": initial("out", j, max(est_out // n, 128),
                           _OUT_CAP_UNBOUNDED),
        }

    while True:
        # Per-iteration static capacities: each partition join's input
        # capacity is the previous expansion's output capacity.
        caps: list = []
        cur_cap = cap
        for j, setup in enumerate(setups):
            caps.append(cur_cap)
            if setup.strategy == "partition":
                cur_cap = quotas[j]["out"]
        final_cap = cur_cap

        local_rep = dist._RepChunk(
            capacity=final_cap,
            columns={c.name: rep_columns[c.name]
                     for c in bottom.schema})
        prepared_b = prepare(bottom, local_rep)
        inter_rep = dist._RepChunk(
            capacity=n * prepared_b.out_capacity,
            columns={c.name: dist._RepColumn(type=c.type,
                                             dictionary=c.vocab)
                     for c in prepared_b.output})
        prepared_f = prepare(front, inter_rep)
        out_cap_b = prepared_b.out_capacity

        quota_state = tuple(
            (j, quotas[j]["qs"], quotas[j]["qf"], quotas[j]["out"])
            for j in sorted(quotas))

        def build(quota_state=quota_state, caps=tuple(caps),
                  prepared_b=prepared_b, prepared_f=prepared_f,
                  out_cap_b=out_cap_b):
            q = {j: (qs, qf, oc) for j, qs, qf, oc in quota_state}

            def fused(columns, row_valid, jbnd, rep_args_t, f_shards_t,
                      b_bnd, f_bnd):
                cur = dict(columns)
                mask = row_valid
                for _name, lo_slot, hi_slot in push_slots:
                    d, v = cur[_name]
                    mask = mask & v & (d >= jbnd[lo_slot]) & \
                        (d <= jbnd[hi_slot])
                telemetry = []
                mesh_mats = []          # armed: n*n matrices per exchange
                for j, setup in enumerate(setups):
                    cur_cap_j = caps[j]
                    ctx = EmitContext(columns=cur, bindings=jbnd,
                                      capacity=cur_cap_j)
                    self_keys = _emit_encoded_keys(
                        setup.self_bound, setup.self_slots, ctx)
                    zero = jnp.zeros((), dtype=jnp.int64)
                    if setup.strategy == "broadcast":
                        a0, a1 = setup.arg_slice
                        pulled, mask = probe_replicated(
                            rep_args_t[a0:a1], setup.n_keys, setup.f_cap,
                            self_keys, mask, setup.join.is_left)
                        for (flat, _f), plane in zip(setup.flat_names,
                                                     pulled):
                            cur[flat] = plane
                        actual = jax.lax.psum(
                            mask.sum().astype(jnp.int64), SHARD_AXIS)
                        telemetry.extend([zero, zero, zero, actual])
                        continue
                    # -- partition join ------------------------------
                    qs, qf, oc = q[j]
                    S, F = n * qs, n * qf
                    is_left = setup.join.is_left
                    fcols, fvalid = f_shards_t[setup.f_shard_index]
                    fctx = EmitContext(columns=fcols, bindings=jbnd,
                                       capacity=setup.f_slice)
                    f_keys = _emit_encoded_keys(
                        setup.f_bound, setup.foreign_slots, fctx)
                    pid_s = _join_pid(self_keys, mask, n, is_left)
                    pid_f = _join_pid(f_keys, fvalid, n, False)
                    cells_s = transfer_counts(pid_s, pid_s < n, n)
                    cells_f = transfer_counts(pid_f, pid_f < n, n)
                    if armed:
                        mesh_mats.append(jax.lax.all_gather(
                            cells_s,
                            SHARD_AXIS).reshape(-1).astype(jnp.int64))
                        mesh_mats.append(jax.lax.all_gather(
                            cells_f,
                            SHARD_AXIS).reshape(-1).astype(jnp.int64))
                    recv_s, mask_s = route_rows(cur, pid_s, n, qs,
                                                cur_cap_j)
                    recv_f, mask_f = route_rows(fcols, pid_f, n, qf,
                                                setup.f_slice)
                    sctx = EmitContext(columns=recv_s, bindings=jbnd,
                                       capacity=S)
                    s_keys = _emit_encoded_keys(
                        setup.self_bound, setup.self_slots, sctx)
                    rctx = EmitContext(columns=recv_f, bindings=jbnd,
                                       capacity=F)
                    r_keys = _emit_encoded_keys(
                        setup.f_bound, setup.foreign_slots, rctx)
                    f_order, f_sorted = sort_foreign_keys(r_keys, mask_f)
                    n_f = mask_f.sum()
                    lo = _lex_searchsorted(f_sorted, n_f, F, s_keys,
                                           "left")
                    hi = _lex_searchsorted(f_sorted, n_f, F, s_keys,
                                           "right")
                    s_null = null_key_mask(s_keys)
                    counts = jnp.where(mask_s & ~s_null, hi - lo, 0)
                    per_row = jnp.where(mask_s, jnp.maximum(counts, 1),
                                        0) if is_left else counts
                    offsets = jnp.cumsum(per_row)
                    total = offsets[-1]
                    starts = jnp.concatenate(
                        [jnp.zeros(1, dtype=offsets.dtype),
                         offsets[:-1]])
                    out_idx = jnp.arange(oc)
                    self_row = jnp.clip(
                        jnp.searchsorted(offsets, out_idx, side="right"),
                        0, S - 1)
                    within = out_idx - starts[self_row]
                    matched = counts[self_row] > 0
                    f_pos = jnp.clip(lo[self_row] + within, 0, F - 1)
                    f_row = f_order[f_pos]
                    live = out_idx < total
                    nxt = {}
                    for name in sorted(cur):
                        d, v = recv_s[name]
                        nxt[name] = (d[self_row],
                                     v[self_row] & live)
                    for flat, fname in setup.flat_names:
                        d, v = recv_f[fname]
                        nxt[flat] = (d[f_row],
                                     v[f_row] & live & matched)
                    cur = nxt
                    mask = live
                    # Demands (replicated via collectives): true max
                    # transfer cells + max per-device expansion.
                    ds = jax.lax.pmax(
                        cells_s.max().astype(jnp.int64), SHARD_AXIS)
                    df = jax.lax.pmax(
                        cells_f.max().astype(jnp.int64), SHARD_AXIS)
                    dout = jax.lax.pmax(total.astype(jnp.int64),
                                        SHARD_AXIS)
                    actual = jax.lax.psum(
                        live.sum().astype(jnp.int64), SHARD_AXIS)
                    telemetry.extend([ds, df, dout, actual])
                # -- bottom per device, all_gather, replicated front --
                planes, cnt = prepared_b.run(cur, mask, b_bnd)
                shard_mask = jnp.arange(out_cap_b) < cnt
                gathered, g_mask = _gathered(
                    list(zip(prepared_b.output, planes)), shard_mask,
                    out_cap_b)
                out_planes, out_count = prepared_f.run(gathered, g_mask,
                                                       f_bnd)
                over = jnp.zeros((), dtype=jnp.int64)
                for j, (_j, qs, qf, oc) in enumerate(quota_state):
                    base = 4 * _j
                    over = jnp.maximum(
                        over, (telemetry[base] > qs).astype(jnp.int64))
                    over = jnp.maximum(
                        over,
                        (telemetry[base + 1] > qf).astype(jnp.int64))
                    over = jnp.maximum(
                        over,
                        (telemetry[base + 2] > oc).astype(jnp.int64))
                final = jnp.stack(
                    [out_count.astype(jnp.int64), over] + telemetry)
                if armed:
                    # Mesh telemetry lanes (ISSUE 20) append AFTER the
                    # existing layout — same stacked transfer.
                    final = jnp.concatenate(
                        [final] + _mesh_lanes(row_valid, cnt)
                        + mesh_mats)
                return out_planes, final

            mapped = shard_map(
                fused, mesh=mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P(),
                          P(SHARD_AXIS), P(), P()),
                out_specs=P(), check_vma=False)

            def program(columns, row_valid, jbnd, rep_args_t, f_shards_t,
                        b_bnd, f_bnd):
                columns, row_valid = _constrain_inputs(
                    mesh, shardings, columns, row_valid)
                return mapped(columns, row_valid, jbnd, rep_args_t,
                              f_shards_t, b_bnd, f_bnd)

            return program

        key = ("whole", "join", plan_fingerprint(plan_x), n, cap, token,
               quota_state, tuple(fingerprint_parts),
               tuple(bind_structure),
               tuple((tuple(b.shape), str(b.dtype))
                     for b in join_bindings),
               prepared_b.binding_shapes(), prepared_f.binding_shapes(),
               rules_fingerprint(rules), armed)
        args = (columns, table.row_valid, join_bindings, tuple(rep_args),
                tuple(f_shards), tuple(prepared_b.bindings),
                tuple(prepared_f.bindings))
        out_planes, final = evaluator._dispatch_spmd(key, build, args)
        # Noted PER read: an overflow retry performs a real second
        # stacked transfer and the counter must say so.
        dist._note_host_sync()
        vals = _read_counts(final)
        count, over = int(vals[0]), int(vals[1])
        if not over:
            break
        if stats is not None:
            stats.whole_plan_retries += 1
        escalated = False
        for j, setup in enumerate(setups):
            if setup.strategy != "partition":
                continue
            dem_s, dem_f, dem_o = (int(vals[2 + 4 * j]),
                                   int(vals[3 + 4 * j]),
                                   int(vals[4 + 4 * j]))
            q = quotas[j]
            if dem_s > q["qs"]:
                bound = caps[j]
                if q["qs"] >= bound:
                    raise YtError(
                        "fused join exchange overflowed at the maximal "
                        f"quota (join {j}, quota={q['qs']}, "
                        f"demand={dem_s})",
                        code=EErrorCode.QueryExecutionError)
                q["qs"] = min(bound,
                              max(pad_capacity(
                                  max(int(dem_s * headroom), 1)),
                                  q["qs"] * 2))
                escalated = True
            if dem_f > q["qf"]:
                bound = setup.f_slice
                if q["qf"] >= bound:
                    raise YtError(
                        "fused join exchange overflowed at the maximal "
                        f"quota (join {j}, quota={q['qf']}, "
                        f"demand={dem_f})",
                        code=EErrorCode.QueryExecutionError)
                q["qf"] = min(bound,
                              max(pad_capacity(
                                  max(int(dem_f * headroom), 1)),
                                  q["qf"] * 2))
                escalated = True
            if dem_o > q["out"]:
                q["out"] = max(pad_capacity(
                    max(int(dem_o * headroom), 1)), q["out"] * 2)
                escalated = True
        if not escalated:
            raise YtError("fused join overflow without a demand above "
                          "quota — telemetry inconsistent",
                          code=EErrorCode.QueryExecutionError)

    # Settle quotas (hysteresis via _settle_quota) + EXPLAIN telemetry.
    for j, setup in enumerate(setups):
        if setup.strategy == "partition":
            dem_s, dem_f, dem_o = (int(vals[2 + 4 * j]),
                                   int(vals[3 + 4 * j]),
                                   int(vals[4 + 4 * j]))
            _settle_quota(evaluator._quota_memo, memo_base + (j, "qs"),
                          dem_s, caps[j])
            _settle_quota(evaluator._quota_memo, memo_base + (j, "qf"),
                          dem_f, setup.f_slice)
            _settle_quota(evaluator._quota_memo, memo_base + (j, "out"),
                          dem_o, _OUT_CAP_UNBOUNDED)
    if stats is not None:
        for j, (setup, decision) in enumerate(zip(setups, decisions)):
            stats.note_join_stage(
                j, setup.join.foreign_table, setup.strategy,
                est_rows=decision.est_out,
                actual_rows=int(vals[5 + 4 * j]))
    if armed:
        base = 2 + 4 * len(setups)
        in_rows, out_rows, off = _mesh_slices(vals, base, n)
        exchanges: list = []
        stages_meta: list = []
        for j, (setup, decision) in enumerate(zip(setups, decisions)):
            actual = int(vals[5 + 4 * j])
            stages_meta.append({
                "stage": j, "table": setup.join.foreign_table,
                "strategy": setup.strategy,
                "est_rows": int(decision.est_out),
                "actual_rows": actual,
                "drift": planner.est_drift(decision.est_out, actual)})
            if setup.strategy != "partition":
                continue
            q = quotas[j]
            self_bytes, f_bytes = stage_row_bytes[j]
            exchanges.append(_mesh_exchange_entry(
                f"join[{j}]/self", vals[off: off + n * n],
                int(vals[2 + 4 * j]), q["qs"], self_bytes))
            off += n * n
            exchanges.append(_mesh_exchange_entry(
                f"join[{j}]/foreign", vals[off: off + n * n],
                int(vals[3 + 4 * j]), q["qf"], f_bytes))
            off += n * n
        _publish_mesh(stats, plan_fingerprint(plan_x), key,
                      _mesh_block(n, in_rows, out_rows, exchanges,
                                  stages=stages_meta))
    return dist._assemble_chunk(prepared_f.output, out_planes, count)
