"""Chunk merger: background compaction of small static-table chunks.

Ref: server/master/chunk_server/chunk_merger.h:136 — masters walk
tables accumulating many small chunks (append-heavy write patterns) and
merge runs of them into fewer, larger chunks, so reads stop paying
per-chunk overhead and the chunk count stays bounded.

TPU-first redesign: the merge itself is one device concat over the
columnar planes (`concat_chunks` — vocabulary unification included),
not a row-by-row rewriting job.  The swap is a compare-and-set under
the master mutation lock: the expensive read+concat runs OUTSIDE the
lock against a snapshot of @chunk_ids, and the table only adopts the
merged chunk if its chunk list is still exactly that snapshot —
concurrent writers win, the merger retries next scan.  Old chunks are
NOT deleted here: copied tables share chunk ids, so reclamation stays
with the reference-counting GC (client.collect_garbage).
"""

from __future__ import annotations

import threading
from typing import Optional

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("chunk_merger")

DEFAULT_MIN_CHUNK_ROWS = 1 << 20        # below this a chunk is "small"
DEFAULT_MAX_MERGE_CHUNKS = 16           # cap per merged output


class ChunkMerger:
    """Scans the metadata tree for mergeable static tables."""

    def __init__(self, client, min_chunk_rows: int = DEFAULT_MIN_CHUNK_ROWS,
                 max_merge_chunks: int = DEFAULT_MAX_MERGE_CHUNKS,
                 interval: float = 30.0):
        self.client = client
        self.min_chunk_rows = min_chunk_rows
        self.max_merge_chunks = max_merge_chunks
        self.interval = interval
        self.stats = {"scans": 0, "tables_merged": 0,
                      "chunks_merged_away": 0, "cas_races_lost": 0}
        # path → (chunk-id tuple, row counts): an unchanged table whose
        # stats predate $row_count is decoded at most once per process.
        self._row_count_memo: \
            "dict[str, tuple[tuple, list[int]]]" = {}
        self._stop = threading.Event()
        self._thread: "Optional[threading.Thread]" = None

    _MEMO_LIMIT = 512          # stats-less tables memoized at once

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "ChunkMerger":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="chunk-merger")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scan_once()
            except Exception:   # noqa: BLE001 — background scan survives
                logger.exception("chunk merger scan failed")

    # -- scanning --------------------------------------------------------------

    def _table_paths(self) -> "list[str]":
        """Static tables with a chunk list, discovered from the tree
        (runs in the primary; the merger is a master-side service)."""
        master = self.client.cluster.master
        out: list[str] = []
        with master.mutation_lock:
            stack = [("/", master.tree.root)]
            while stack:
                path, node = stack.pop()
                for name, child in list(node.children.items()):
                    child_path = f"//{name}" if path == "/" else \
                        f"{path}/{name}"
                    if child.type == "table" and \
                            not child.attributes.get("dynamic") and \
                            child.attributes.get("chunk_ids"):
                        out.append(child_path)
                    stack.append((child_path, child))
        return out

    def scan_once(self) -> int:
        """One pass over every static table; returns tables merged."""
        self.stats["scans"] += 1
        merged = 0
        for path in self._table_paths():
            try:
                if self._merge_table(path):
                    merged += 1
            except YtError as exc:
                logger.warning("merge of %s failed: %s", path, exc)
        return merged

    def _merge_plan(self, chunk_ids: "list[str]",
                    row_counts: "list[int]") -> "list[tuple[int, int]]":
        """[start, end) runs of ADJACENT small chunks worth merging —
        adjacency preserves both static row order and sorted-table key
        order (neighbor ranges abut)."""
        runs: list[tuple[int, int]] = []
        i = 0
        n = len(chunk_ids)
        while i < n:
            if row_counts[i] >= self.min_chunk_rows:
                i += 1
                continue
            j = i
            total = 0
            while j < n and row_counts[j] < self.min_chunk_rows and \
                    j - i < self.max_merge_chunks and \
                    total + row_counts[j] < 2 * self.min_chunk_rows:
                total += row_counts[j]
                j += 1
            if j - i >= 2:
                runs.append((i, j))
            i = max(j, i + 1)
        return runs

    def _row_counts(self, path: str, node,
                    snapshot_ids: "list[str]") -> "list[int]":
        """Per-chunk row counts from METADATA when available ($row_count
        in the aligned @chunk_stats); decoding every chunk of every
        table each scan would thrash the cache proportionally to total
        data size.  Old tables without the key decode once and memoize."""
        old_stats = list(node.attributes.get("chunk_stats") or [])
        if len(old_stats) == len(snapshot_ids) and \
                all(isinstance(s, dict) and "$row_count" in s
                    for s in old_stats):
            return [int(s["$row_count"]) for s in old_stats]
        ids = tuple(snapshot_ids)
        cached = self._row_count_memo.get(path)
        if cached is None or cached[0] != ids:
            counts = [self.client.cluster.chunk_cache.get(cid).row_count
                      for cid in snapshot_ids]
            # Keyed PER PATH (one entry per table, replaced when its
            # chunk list changes): a scan over many stats-less tables
            # must not evict each other's memo every table, or every
            # scan re-decodes every chunk of every such table.  Bounded
            # FIFO so deleted/renamed tables cannot leak entries in a
            # long-lived master process.
            while len(self._row_count_memo) >= self._MEMO_LIMIT:
                self._row_count_memo.pop(
                    next(iter(self._row_count_memo)))
            self._row_count_memo[path] = (ids, counts)
            return counts
        return cached[1]

    def _merge_table(self, path: str) -> bool:
        from ytsaurus_tpu.chunks.columnar import concat_chunks

        client = self.client
        master = client.cluster.master
        node = master.tree.try_resolve(path)
        if node is None or node.attributes.get("dynamic"):
            return False
        snapshot_ids = list(node.attributes.get("chunk_ids") or [])
        if len(snapshot_ids) < 2:
            return False
        runs = self._merge_plan(snapshot_ids,
                                self._row_counts(path, node,
                                                 snapshot_ids))
        if not runs:
            return False
        # Expensive device work OUTSIDE the mutation lock — only the
        # chunks in merge runs are fetched.  New chunks are registered
        # as protected BEFORE they hit the store: a concurrent GC sweep
        # in the write→CAS window must not reclaim them.
        protected = client.cluster.protected_chunk_ids
        replacements = []               # (start, end, new_id, new_stats)
        try:
            for start, end in runs:
                merged = concat_chunks(
                    [client.cluster.chunk_cache.get(cid)
                     for cid in snapshot_ids[start:end]])
                new_id = client.cluster.chunk_store.write_chunk(merged)
                protected.add(new_id)
                # Stats were computed inside the serialize pass (chunk
                # meta); read_stats is a meta parse, so the unprotected
                # window stays write→add sized.
                stats = client.cluster.chunk_store.read_stats(new_id)
                replacements.append((start, end, new_id, stats))
        except BaseException:
            protected.difference_update(
                r[2] for r in replacements)
            raise
        new_ids: list[str] = []
        new_stats: list = []
        old_stats = list(node.attributes.get("chunk_stats") or [])
        stats_aligned = len(old_stats) == len(snapshot_ids)
        cursor = 0
        for start, end, new_id, stats in replacements:
            new_ids.extend(snapshot_ids[cursor:start])
            if stats_aligned:
                new_stats.extend(old_stats[cursor:start])
            new_ids.append(new_id)
            new_stats.append(stats)
            cursor = end
        new_ids.extend(snapshot_ids[cursor:])
        if stats_aligned:
            new_stats.extend(old_stats[cursor:])
        try:
            with master.mutation_lock:
                live = master.tree.try_resolve(path)
                current = list(live.attributes.get("chunk_ids") or []) \
                    if live is not None else None
                if current != snapshot_ids:
                    # A writer won the race; the freshly written merged
                    # chunks are unreferenced and fall to GC.
                    self.stats["cas_races_lost"] += 1
                    return False
                client.set(path + "/@chunk_ids", new_ids)
                if stats_aligned:
                    client.set(path + "/@chunk_stats", new_stats)
                elif self.client.exists(path + "/@chunk_stats"):
                    client.remove(path + "/@chunk_stats", force=True)
        finally:
            # Published (tree-referenced) or lost (garbage): either way
            # the protection window is over.
            protected.difference_update(r[2] for r in replacements)
        self.stats["tables_merged"] += 1
        self.stats["chunks_merged_away"] += \
            len(snapshot_ids) - len(new_ids)
        logger.info("merged %s: %d -> %d chunks", path,
                    len(snapshot_ids), len(new_ids))
        return True
