"""YSON round-trip tests (ref core/yson/unittests)."""

import math

import pytest

from ytsaurus_tpu import yson
from ytsaurus_tpu.yson import YsonEntity, YsonUint64, to_yson_type


CASES = [
    None,
    True,
    False,
    0,
    -1,
    2**62,
    -(2**63),
    YsonUint64(2**64 - 1),
    1.5,
    -2.25,
    "hello",
    "with spaces and \"quotes\"",
    "",
    b"\x00\xff\x01binary" if False else "unicode ok",
    [],
    [1, 2, 3],
    {"a": 1, "b": [True, None]},
    {"nested": {"x": {"y": [1.0, "z"]}}},
]


@pytest.mark.parametrize("binary", [False, True])
@pytest.mark.parametrize("value", CASES, ids=[repr(c)[:30] for c in CASES])
def test_roundtrip(value, binary):
    blob = yson.dumps(value, binary=binary)
    back = yson.loads(blob)
    assert back == value


def test_binary_bytes_roundtrip():
    raw = bytes(range(256))
    blob = yson.dumps(raw, binary=True)
    back = yson.loads(blob, encoding=None)
    assert back == raw


def test_text_escaped_bytes_roundtrip():
    raw = b"\x00\xff\"quote\\slash\n"
    blob = yson.dumps(raw, binary=False)
    back = yson.loads(blob, encoding=None)
    assert back == raw


def test_attributes_roundtrip():
    value = to_yson_type({"a": 1}, {"attr": "x", "n": 2})
    for binary in (False, True):
        back = yson.loads(yson.dumps(value, binary=binary))
        assert back == {"a": 1}
        assert back.attributes == {"attr": "x", "n": 2}


def test_entity_with_attributes():
    value = to_yson_type(None, {"type": "table"})
    back = yson.loads(yson.dumps(value))
    assert isinstance(back, YsonEntity)
    assert back.attributes == {"type": "table"}


def test_uint64_suffix_text():
    assert yson.loads(b"5u") == 5
    assert isinstance(yson.loads(b"5u"), YsonUint64)
    assert yson.dumps(YsonUint64(5)) == b"5u"


def test_special_doubles():
    assert math.isnan(yson.loads(yson.dumps(float("nan"))))
    assert yson.loads(yson.dumps(float("inf"))) == float("inf")
    assert yson.loads(yson.dumps(float("-inf"))) == float("-inf")


def test_text_format_examples():
    # Hand-written text forms parse as expected.
    assert yson.loads(b"{a=1;b=[x;y];c=#}") == \
        {"a": 1, "b": ["x", "y"], "c": None}
    assert yson.loads(b"<append=%true>//tmp/t").attributes == {"append": True}
    assert yson.loads(b" { a = 1 ; } ") == {"a": 1}


def test_list_fragment():
    rows = yson.loads(b"{a=1};{a=2};{a=3}", yson_type="list_fragment")
    assert rows == [{"a": 1}, {"a": 2}, {"a": 3}]


def test_parse_error_position():
    from ytsaurus_tpu import YtError
    with pytest.raises(YtError):
        yson.loads(b"{a=}")
    with pytest.raises(YtError):
        yson.loads(b"[1;2")


def test_malformed_inputs_raise_yterror():
    from ytsaurus_tpu import YtError
    for blob in [b'"abc\\', b'\x03\x01\x02', b'1.2.3', b'{a=1', b'\x01\xff\xff']:
        with pytest.raises(YtError):
            yson.loads(blob)
