"""Tiled radix sort engine (ops/radix.py) + exact group ordering.

Correctness oracle: numpy stable sorts.  The radix engine must match the
variadic-network engine bit-for-bit (same stable order) for every key
shape, because stable_argsort_u32 dispatches between them by size.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from ytsaurus_tpu.ops.radix import radix_argsort_u32
from ytsaurus_tpu.ops.segments import (
    hash_group_order,
    pack_key_planes_bits,
    packed_sort_indices,
    segment_boundaries,
    stable_argsort_u32,
)


def _np_stable_argsort(words):
    # np.lexsort takes minor key FIRST; words are major-first.
    return np.lexsort(tuple(np.asarray(w) for w in reversed(words)))


@pytest.mark.parametrize("n", [1, 2, 5, 100, 2048, 2049, 5000, 100_000])
def test_radix_single_word(n):
    rng = np.random.default_rng(n)
    word = jnp.asarray(rng.integers(0, 1 << 32, n, dtype=np.uint32))
    got = np.asarray(radix_argsort_u32([word]))
    expect = _np_stable_argsort([word])
    np.testing.assert_array_equal(got, expect)


@pytest.mark.parametrize("engine", ["gather", "scatter"])
def test_radix_multi_word(engine):
    rng = np.random.default_rng(7)
    n = 10_000
    keys = rng.integers(0, 1 << 63, n, dtype=np.uint64)
    hi = jnp.asarray((keys >> 32).astype(np.uint32))
    lo = jnp.asarray(keys.astype(np.uint32))
    got = np.asarray(radix_argsort_u32([hi, lo], engine=engine))
    expect = _np_stable_argsort([hi, lo])
    np.testing.assert_array_equal(got, expect)


@pytest.mark.parametrize("engine", ["gather", "scatter"])
def test_radix_empty_input(engine):
    """ADVICE r3: a forced engine must return an empty permutation for
    n=0, not crash on degenerate tile math."""
    empty = jnp.zeros((0,), jnp.uint32)
    got = np.asarray(radix_argsort_u32([empty], engine=engine))
    assert got.shape == (0,)
    assert got.dtype == np.uint32


def test_radix_stability_with_duplicates():
    rng = np.random.default_rng(3)
    n = 50_000
    word = jnp.asarray(rng.integers(0, 7, n, dtype=np.uint32))
    got = np.asarray(radix_argsort_u32([word]))
    expect = _np_stable_argsort([word])
    np.testing.assert_array_equal(got, expect)      # ties keep input order


def test_radix_word_bits_skips_high_bytes():
    rng = np.random.default_rng(11)
    n = 30_000
    word = jnp.asarray(rng.integers(0, 1 << 12, n, dtype=np.uint32))
    got = np.asarray(radix_argsort_u32([word], word_bits=[12]))
    expect = _np_stable_argsort([word])
    np.testing.assert_array_equal(got, expect)


def test_radix_all_equal_and_extremes():
    n = 4096
    ones = jnp.full(n, 0xFFFFFFFF, dtype=jnp.uint32)
    np.testing.assert_array_equal(np.asarray(radix_argsort_u32([ones])),
                                  np.arange(n))
    zeros = jnp.zeros(n, dtype=jnp.uint32)
    np.testing.assert_array_equal(np.asarray(radix_argsort_u32([zeros])),
                                  np.arange(n))


def test_engine_dispatch_matches_network(monkeypatch):
    rng = np.random.default_rng(5)
    n = 20_000
    w1 = jnp.asarray(rng.integers(0, 50, n, dtype=np.uint32))
    w2 = jnp.asarray(rng.integers(0, 1 << 32, n, dtype=np.uint32))
    monkeypatch.setenv("YT_TPU_SORT_ENGINE", "network")
    a = np.asarray(stable_argsort_u32([w1, w2]))
    monkeypatch.setenv("YT_TPU_SORT_ENGINE", "radix")
    b = np.asarray(stable_argsort_u32([w1, w2]))
    monkeypatch.setenv("YT_TPU_SORT_ENGINE", "lsd32")
    c = np.asarray(stable_argsort_u32([w1, w2]))
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, c)


def test_packed_sort_small_fields_radix(monkeypatch):
    """Packed small fields (null bit + value bits in one word) sort the
    same under the radix engine, including the shifted tail word."""
    rng = np.random.default_rng(9)
    n = 10_000
    data = jnp.asarray(rng.integers(0, 30, n, dtype=np.int64))
    valid = jnp.asarray(rng.random(n) > 0.1)
    items = [(data, valid, False, 5),
             (jnp.asarray(rng.integers(0, 4, n, dtype=np.int64)),
              jnp.ones(n, dtype=bool), True, 2)]
    monkeypatch.setenv("YT_TPU_SORT_ENGINE", "network")
    a = np.asarray(packed_sort_indices(items))
    monkeypatch.setenv("YT_TPU_SORT_ENGINE", "radix")
    b = np.asarray(packed_sort_indices(items))
    np.testing.assert_array_equal(a, b)
    words, bits = pack_key_planes_bits(items)
    assert len(words) == 1 and bits == [9]       # 1+5 + 1+2 bits packed


@pytest.mark.parametrize("engine", ["network", "radix"])
def test_group_order_exact_null_vs_zero(monkeypatch, engine):
    """NULL and literal 0 are distinct groups; masked rows sort last;
    group identity is exact (no hash involved)."""
    monkeypatch.setenv("YT_TPU_SORT_ENGINE", engine)
    data = jnp.asarray([0, 5, 0, 5, 0, 7], dtype=jnp.int64)
    valid = jnp.asarray([True, True, False, True, True, True])
    mask = jnp.asarray([True, True, True, True, True, False])
    order = np.asarray(hash_group_order([(data, valid)], mask))
    # Masked row (index 5) last.
    assert order[-1] == 5
    sorted_keys = [(data[order], valid[order])]
    seg, nseg = segment_boundaries(sorted_keys, mask[order])
    # Groups: NULL, 0, 5 -> 3 groups (7 is masked out).
    assert int(nseg) == 3
    # The NULL row (2) must not group with the zero rows (0, 4).
    seg = np.asarray(seg)
    pos = {int(r): seg[i] for i, r in enumerate(order)}
    assert pos[0] == pos[4]
    assert pos[2] != pos[0]
    assert pos[1] == pos[3]


@pytest.mark.slow   # ~13s property sweep; tier-1 keeps radix/group-order
# coverage via the single/multi-word, stability, and null-vs-zero tests.
def test_group_order_multi_key_adjacency():
    rng = np.random.default_rng(17)
    n = 30_000
    k1 = jnp.asarray(rng.integers(-50, 50, n, dtype=np.int64))
    v1 = jnp.asarray(rng.random(n) > 0.05)
    k2 = jnp.asarray(rng.random(n).astype(np.float64) * 4 // 1)
    v2 = jnp.asarray(rng.random(n) > 0.05)
    mask = jnp.asarray(rng.random(n) > 0.1)
    order = np.asarray(hash_group_order([(k1, v1), (k2, v2)], mask))
    # Every (key-tuple) group must be CONTIGUOUS among unmasked rows.
    mask_np = np.asarray(mask)
    rows = [(bool(mask_np[i]),
             (None if not v1[i] else int(k1[i]),
              None if not v2[i] else float(k2[i])))
            for i in np.asarray(order)]
    unmasked = [key for m, key in rows if m]
    assert all(not m for m, _ in rows[len(unmasked):])   # masked tail
    seen = set()
    prev = object()
    for key in unmasked:
        if key != prev:
            assert key not in seen, f"group {key} fragmented"
            seen.add(key)
            prev = key


@pytest.mark.parametrize("n", [5, 2048, 10_000])
def test_pallas_engine_matches(n):
    """Pallas counting-pass engine (interpret mode off-TPU) matches the
    oracle bit-for-bit."""
    rng = np.random.default_rng(n)
    keys = rng.integers(0, 1 << 63, n, dtype=np.uint64)
    hi = jnp.asarray((keys >> 32).astype(np.uint32))
    lo = jnp.asarray(keys.astype(np.uint32))
    got = np.asarray(radix_argsort_u32([hi, lo], engine="pallas"))
    expect = _np_stable_argsort([hi, lo])
    np.testing.assert_array_equal(got, expect)


def test_pallas_hist_rank_direct():
    from ytsaurus_tpu.ops.pallas_radix import hist_rank
    rng = np.random.default_rng(2)
    n, bits = 8192, 6
    d = rng.integers(0, 1 << bits, n, dtype=np.int32)
    counts, rank = hist_rank(jnp.asarray(d), bits=bits, tile=2048)
    counts, rank = np.asarray(counts), np.asarray(rank)
    nt = n // 2048
    for t in range(nt):
        seg = d[t * 2048:(t + 1) * 2048]
        np.testing.assert_array_equal(counts[t],
                                      np.bincount(seg, minlength=1 << bits))
        seen = {}
        for i, b in enumerate(seg):
            assert rank[t * 2048 + i] == seen.get(b, 0)
            seen[b] = seen.get(b, 0) + 1
