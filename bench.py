"""Benchmarks for the BASELINE.md configs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The default (headline) config is TPC-H Q1 rows/sec (config 1); the others
are selectable with --config:

  q1      scan + filter + 8-aggregate GROUP BY (headline; default)
  groupby GROUP BY key over a sorted table (hash-aggregate path, config 2)
  topk    ORDER BY ... LIMIT K (config 3)
  q3      two-table JOIN + GROUP BY + top-K (TPC-H Q3, config 4)
  sort    device sort (single-chip stand-in for the 1B-row Sort, config 5)

Baseline: the reference's LLVM-JIT evaluator on a modern x86 core sustains
roughly 5e7 rows/s on Q1-shaped scan+filter+group (order-of-magnitude from
vectorized-engine literature; the reference repo publishes no absolute
numbers — see BASELINE.md).  vs_baseline = ours / 5e7 for the query configs.

NOTE: under the axon tunnel, jax.block_until_ready does NOT synchronize —
timings force a real device→host read instead.

Usage: python bench.py [--config NAME] [--smoke] [--rows N] [--iters K]
"""

import argparse
import json
import sys
import time


BASELINE_ROWS_PER_SEC = 5.0e7


def _sync(x):
    """True synchronization: force a host read (see module note)."""
    import numpy as np
    leaf = x
    while isinstance(leaf, (list, tuple)):
        leaf = leaf[0]
    np.asarray(leaf).ravel()[:1]


def _time_plan(query, tables, iters, evaluator=None):
    """Compile + time one plan over prepared chunks; returns best seconds."""
    import jax

    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.engine.lowering import prepare

    schemas = {path: chunk.schema for path, chunk in tables.items()}
    plan = build_query(query, schemas)
    chunk = tables[plan.source]
    prepared = prepare(plan, chunk)
    columns = {c.name: (chunk.columns[c.name].data,
                        chunk.columns[c.name].valid)
               for c in plan.schema}
    bindings = tuple(prepared.bindings)
    row_valid = chunk.row_valid
    fn = jax.jit(prepared.run)
    planes, count = fn(columns, row_valid, bindings)   # warm-up / compile
    _sync(planes)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        planes, count = fn(columns, row_valid, bindings)
        _sync(planes)
        times.append(time.perf_counter() - t0)
    return min(times), int(count)


def bench_q1(n_rows, iters):
    from ytsaurus_tpu.models import tpch
    chunk = tpch.generate_lineitem(n_rows)
    best, groups = _time_plan(tpch.Q1, {"//tpch/lineitem": chunk}, iters)
    assert 1 <= groups <= 6
    return "tpch_q1_rows_per_sec", n_rows / best, best

def bench_groupby(n_rows, iters):
    import numpy as np
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.schema import TableSchema
    rng = np.random.default_rng(0)
    schema = TableSchema.make([("k", "int64", "ascending"), ("g", "int64"),
                               ("v", "int64")])
    chunk = ColumnarChunk.from_arrays(schema, {
        "k": np.arange(n_rows), "g": rng.integers(0, 10_000, n_rows),
        "v": rng.integers(0, 1000, n_rows)})
    best, _ = _time_plan(
        "g, sum(v) AS s, count(*) AS c FROM [//t] GROUP BY g",
        {"//t": chunk}, iters)
    return "groupby_rows_per_sec", n_rows / best, best

def bench_topk(n_rows, iters):
    import numpy as np
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.schema import TableSchema
    rng = np.random.default_rng(0)
    schema = TableSchema.make([("k", "int64"), ("v", "double")])
    chunk = ColumnarChunk.from_arrays(schema, {
        "k": np.arange(n_rows), "v": rng.uniform(0, 1, n_rows)})
    best, count = _time_plan(
        "k, v FROM [//t] ORDER BY v DESC LIMIT 100", {"//t": chunk}, iters)
    assert count == 100
    return "topk_rows_per_sec", n_rows / best, best

def bench_q3(n_rows, iters):
    from ytsaurus_tpu.models import tpch
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    n_orders = max(n_rows // 4, 1)
    lineitem = tpch.generate_lineitem(n_rows, n_orders=n_orders)
    orders = tpch.generate_orders(n_orders)
    ev = Evaluator()
    from ytsaurus_tpu.query.builder import build_query
    plan = build_query(tpch.Q3, {"//tpch/lineitem": tpch.LINEITEM_SCHEMA,
                                 "//tpch/orders": tpch.ORDERS_SCHEMA})
    foreign = {"//tpch/orders": orders}
    out = ev.run_plan(plan, lineitem, foreign)      # warm-up (incl. join)
    assert out.row_count <= 10
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = ev.run_plan(plan, lineitem, foreign)
        _sync(out.columns[out.schema.column_names[0]].data)
        times.append(time.perf_counter() - t0)
    best = min(times)
    return "tpch_q3_rows_per_sec", n_rows / best, best

def bench_sort(n_rows, iters):
    import numpy as np
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.operations.sort_op import sort_chunk
    from ytsaurus_tpu.schema import TableSchema
    rng = np.random.default_rng(0)
    schema = TableSchema.make([("k", "int64"), ("p", "double")])
    chunk = ColumnarChunk.from_arrays(schema, {
        "k": rng.integers(0, 1 << 60, n_rows), "p": rng.uniform(0, 1, n_rows)})
    out = sort_chunk(chunk, ["k"])                  # warm-up
    _sync(out.columns["k"].data)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = sort_chunk(chunk, ["k"])
        _sync(out.columns["k"].data)
        times.append(time.perf_counter() - t0)
    return "sort_rows_per_sec", n_rows / min(times), min(times)


_CONFIGS = {
    "q1": (bench_q1, 64_000_000),
    "groupby": (bench_groupby, 16_000_000),
    "topk": (bench_topk, 64_000_000),
    "q3": (bench_q3, 4_000_000),
    "sort": (bench_sort, 16_000_000),
}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", choices=sorted(_CONFIGS), default="q1")
    parser.add_argument("--smoke", action="store_true",
                        help="small row count, CPU-friendly")
    parser.add_argument("--rows", type=int, default=None)
    parser.add_argument("--iters", type=int, default=5)
    args = parser.parse_args()

    from ytsaurus_tpu.utils.backend import ensure_backend
    jax = ensure_backend()

    fn, default_rows = _CONFIGS[args.config]
    n_rows = args.rows or (100_000 if args.smoke else default_rows)
    metric, rows_per_sec, best = fn(n_rows, args.iters)
    print(json.dumps({
        "metric": metric,
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
    }))
    print(f"# config={args.config} n_rows={n_rows} best={best*1e3:.2f}ms "
          f"device={jax.devices()[0].platform}", file=sys.stderr)


if __name__ == "__main__":
    main()
