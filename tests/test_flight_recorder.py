"""Query flight recorder (ISSUE 5): end-to-end tracing, EXPLAIN ANALYZE
profiles, the /traces plane, and the cross-process propagation fixes.

Covers the satellite checklist:
  - multi-hop trace driver → gateway → coordinator → tablet with
    parent/child linkage + tag correctness,
  - RPC server context restoration on executor threads (leaked contexts
    must not poison later requests) and RetryingChannel same-trace/
    fresh-span-per-attempt retries,
  - slow-query log capture + eviction,
  - /traces endpoint round-trip,
  - span ring-buffer bounded memory.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils.tracing import (
    NULL_SPAN,
    SpanCollector,
    SpanRecord,
    TraceContext,
    child_span,
    current_trace,
    get_collector,
    span_tree,
    start_query_span,
    trace_summaries,
)


@pytest.fixture
def tracing_defaults():
    """Restore process-wide tracing config + flight recorder after a test
    that installs a custom TracingConfig."""
    yield yt_config.set_tracing_config
    yt_config.set_tracing_config(None)
    from ytsaurus_tpu.query.profile import get_flight_recorder
    get_flight_recorder().clear()


def _walk(nodes):
    for node in nodes:
        yield node
        yield from _walk(node.get("children") or [])


def _find(nodes, name):
    return [n for n in _walk(nodes) if n["name"] == name]


# -- span primitives ----------------------------------------------------------

def test_child_span_is_null_without_ambient_trace():
    assert current_trace() is None
    span = child_span("orphan", key=1)
    assert span is NULL_SPAN
    with span as s:
        s.add_tag("ignored", True)       # all no-ops
        assert current_trace() is None   # activation touches nothing


def test_child_span_null_under_unsampled_parent():
    with TraceContext("quiet", sampled=False):
        assert child_span("inner") is NULL_SPAN


def test_start_query_span_sampling_and_force(tracing_defaults):
    set_config = tracing_defaults
    set_config(yt_config.TracingConfig(enabled=True, sample_rate=0.0))
    assert start_query_span("q") is NULL_SPAN
    forced = start_query_span("q", force=True)
    assert forced is not NULL_SPAN
    set_config(yt_config.TracingConfig(enabled=False))
    assert start_query_span("q") is NULL_SPAN
    # force overrides even a disabled config (explain_analyze contract).
    assert start_query_span("q", force=True) is not NULL_SPAN


def test_start_query_span_pins_trace_id():
    span = start_query_span("q", force=True, trace_id="feedface" * 4)
    assert span.trace_id == "feedface" * 4
    with span:
        pass
    assert get_collector().find("feedface" * 4)


def test_exception_tagged_on_span():
    ctx = TraceContext("boom")
    with pytest.raises(ValueError):
        with ctx:
            raise ValueError("payload")
    (rec,) = get_collector().find(ctx.trace_id)
    assert "ValueError" in rec.tags["error"]


# -- ring buffer --------------------------------------------------------------

def _record(name="s", trace_id=None):
    ctx = TraceContext(name, trace_id=trace_id)
    ctx.start_time = time.time()
    return SpanRecord(ctx, 0.001)


def test_collector_ring_is_bounded():
    col = SpanCollector(capacity=8)
    for i in range(50):
        col.add(_record(name=f"s{i}"))
    snap = col.snapshot()
    assert len(snap) == 8
    assert [s.name for s in snap] == [f"s{i}" for i in range(42, 50)]
    col.set_capacity(3)                   # shrink drops the oldest
    assert [s.name for s in col.snapshot()] == ["s47", "s48", "s49"]


def test_collector_drain_cursor_preserves_views():
    col = SpanCollector(capacity=16)
    col.add(_record("a"))
    col.add(_record("b"))
    assert [s.name for s in col.drain()] == ["a", "b"]
    # Drained spans are still VISIBLE to the flight-recorder views; only
    # the export cursor advanced.
    assert [s.name for s in col.snapshot()] == ["a", "b"]
    assert col.drain() == []
    col.add(_record("c"))
    assert [s.name for s in col.drain()] == ["c"]


def test_span_tree_structure_and_summaries():
    with TraceContext("root") as root:
        with root.create_child("mid") as mid:
            with mid.create_child("leaf"):
                pass
        with root.create_child("sibling"):
            pass
    tree = span_tree(root.trace_id)
    assert len(tree) == 1 and tree[0]["name"] == "root"
    names = [n["name"] for n in tree[0]["children"]]
    assert names == ["mid", "sibling"]    # start-time ordered
    assert tree[0]["children"][0]["children"][0]["name"] == "leaf"
    (row,) = [r for r in trace_summaries()
              if r["trace_id"] == root.trace_id]
    assert row["root"] == "root" and row["spans"] == 4
    assert span_tree("no-such-trace") == []


# -- RPC propagation regressions (satellite 1) --------------------------------

def test_rpc_server_restores_context_and_isolates_leaks():
    """Handlers run on pooled executor threads: the dispatcher must (a)
    restore the caller's wire context and (b) isolate each request in a
    fresh contextvars copy, so a handler that LEAKS an active context
    cannot poison the next request on the same thread."""
    from ytsaurus_tpu.rpc import Channel, RpcServer
    from ytsaurus_tpu.rpc.server import Service, rpc_method

    seen = []

    class Leaky(Service):
        name = "leaky"

        @rpc_method()
        def leak(self, body, attachments):
            # Enter WITHOUT exiting: the worst-behaved handler.
            TraceContext("leaked").__enter__()
            return {"ok": True}

        @rpc_method()
        def probe(self, body, attachments):
            ctx = current_trace()
            seen.append(ctx.trace_id if ctx is not None else None)
            return {"ok": True}

    # ONE worker thread: every request shares it, maximizing exposure.
    server = RpcServer([Leaky()], max_workers=1)
    server.start()
    channel = Channel(server.address, timeout=10)
    try:
        channel.call("leaky", "leak", {})
        channel.call("leaky", "probe", {})
        assert seen == [None]             # the leak did not escape
        with TraceContext("caller") as root:
            channel.call("leaky", "probe", {})
        assert seen[1] == root.trace_id   # wire context restored
        channel.call("leaky", "probe", {})
        assert seen[2] is None            # and not sticky afterwards
    finally:
        channel.close()
        server.stop()


def test_rpc_server_does_not_root_traces_for_untraced_requests():
    from ytsaurus_tpu.rpc import Channel, RpcServer
    from ytsaurus_tpu.rpc.server import Service, rpc_method

    class Echo(Service):
        name = "echo2"

        @rpc_method()
        def ping(self, body, attachments):
            return {"ok": True}

    server = RpcServer([Echo()])
    server.start()
    channel = Channel(server.address, timeout=10)
    try:
        before = len(get_collector().snapshot())
        channel.call("echo2", "ping", {})
        after = [s for s in get_collector().snapshot()[before:]
                 if s.name == "echo2.ping"]
        assert after == []       # sampling belongs to the entry points
    finally:
        channel.close()
        server.stop()


def test_retrying_channel_fresh_span_per_attempt():
    from ytsaurus_tpu.rpc.channel import RetryingChannel

    calls = []

    class FlakyChannel:
        def call(self, service, method, body, attachments=(),
                 timeout=None):
            ctx = current_trace()
            calls.append((ctx.trace_id, ctx.span_id))
            if len(calls) < 3:
                raise YtError("transport down",
                              code=EErrorCode.TransportError)
            return {"ok": True}, ()

    retrying = RetryingChannel(FlakyChannel(), attempts=4, backoff=0.001)
    with TraceContext("client_root") as root:
        body, _ = retrying.call("svc", "m", {})
    assert body == {"ok": True} and len(calls) == 3
    # Same trace id on every attempt...
    assert {tid for tid, _ in calls} == {root.trace_id}
    # ...but a FRESH span per attempt (no aliasing of server work).
    assert len({sid for _, sid in calls}) == 3
    attempts = sorted(
        s.tags["attempt"] for s in get_collector().find(root.trace_id)
        if s.name == "rpc.call")
    assert attempts == [0, 1, 2]
    # Attempt spans are siblings under the root, not nested chains.
    assert all(s.parent_span_id == root.span_id
               for s in get_collector().find(root.trace_id)
               if s.name == "rpc.call")


# -- execution profiles + flight recorder -------------------------------------

def _profile(wall, query="q", trace_id=None):
    from ytsaurus_tpu.query.profile import ExecutionProfile
    return ExecutionProfile(
        query=query, trace_id=trace_id, pool="default",
        started_at=time.time(), wall_time=wall, admission_wait=0.0,
        compile_time=0.0, execute_time=wall, statistics={})


def test_slow_query_log_capture_and_eviction(tracing_defaults):
    from ytsaurus_tpu.query.profile import get_flight_recorder
    set_config = tracing_defaults
    set_config(yt_config.TracingConfig(
        slow_query_threshold=0.1, slow_log_capacity=3,
        recent_log_capacity=2, sample_rate=0.0))
    rec = get_flight_recorder()
    rec.clear()
    for i in range(6):
        rec.observe(_profile(wall=0.2 + i, query=f"slow{i}"))
    rec.observe(_profile(wall=0.01, query="fast"))
    slow = [p.query for p in rec.slow_queries()]
    assert slow == ["slow3", "slow4", "slow5"]   # bounded, oldest evicted
    assert rec.recent() == []       # sample_rate=0: fast queries dropped
    set_config(yt_config.TracingConfig(
        slow_query_threshold=0.1, slow_log_capacity=3,
        recent_log_capacity=2, sample_rate=1.0))
    for i in range(4):
        rec.observe(_profile(wall=0.01, query=f"fast{i}"))
    assert [p.query for p in rec.recent()] == ["fast2", "fast3"]
    set_config(yt_config.TracingConfig(enabled=False))
    rec.observe(_profile(wall=9.0, query="while_disabled"))
    assert "while_disabled" not in [p.query for p in rec.slow_queries()]


def test_execution_profile_format_and_dict():
    with TraceContext("query.select") as root:
        with root.create_child("serving.admission") as adm:
            adm.add_tag("pool", "default")
    p = _profile(wall=0.5, query="SELECT 1", trace_id=root.trace_id)
    text = p.format()
    assert "SELECT 1" in text and root.trace_id in text
    assert "compile" in text and "execute" in text
    d = p.to_dict(include_rows=False)
    assert d["trace_id"] == root.trace_id
    assert _find(d["span_tree"], "serving.admission")
    assert "rows" not in d


# -- /traces plane ------------------------------------------------------------

def test_traces_endpoint_round_trip(tracing_defaults):
    from ytsaurus_tpu.query.profile import get_flight_recorder
    from ytsaurus_tpu.server.monitoring import MonitoringServer
    from ytsaurus_tpu.server.orchid import OrchidTree
    from ytsaurus_tpu.utils.profiling import ProfilerRegistry

    set_config = tracing_defaults
    set_config(yt_config.TracingConfig(slow_query_threshold=0.1))
    with TraceContext("query.select") as root:
        with root.create_child("coordinator.shard") as shard:
            shard.add_tag("shard", 0)
    get_flight_recorder().clear()
    get_flight_recorder().observe(
        _profile(wall=0.5, query="SELECT slow", trace_id=root.trace_id))

    server = MonitoringServer(OrchidTree(), ProfilerRegistry())
    server.start()
    try:
        base = f"http://{server.address}"
        listing = json.loads(
            urllib.request.urlopen(f"{base}/traces").read())
        assert root.trace_id in [r["trace_id"]
                                 for r in listing["recent_traces"]]
        (slow,) = listing["slow_queries"]
        assert slow["query"] == "SELECT slow"
        assert slow["trace_id"] == root.trace_id
        detail = json.loads(urllib.request.urlopen(
            f"{base}/traces/{root.trace_id}").read())
        assert detail["trace_id"] == root.trace_id
        (tree_root,) = detail["spans"]
        assert tree_root["name"] == "query.select"
        assert tree_root["children"][0]["name"] == "coordinator.shard"
        assert tree_root["children"][0]["tags"] == {"shard": 0}
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/traces/deadbeef")
        assert err.value.code == 404
    finally:
        server.stop()


def test_orchid_flight_recorder_views(tracing_defaults):
    from ytsaurus_tpu.query.profile import get_flight_recorder
    from ytsaurus_tpu.server.orchid import default_orchid

    set_config = tracing_defaults
    set_config(yt_config.TracingConfig(slow_query_threshold=0.1))
    with TraceContext("query.orchid_view") as root:
        pass
    get_flight_recorder().clear()
    get_flight_recorder().observe(
        _profile(wall=1.0, query="Q", trace_id=root.trace_id))
    tree = default_orchid()
    traces = tree.get("/tracing/traces")
    assert root.trace_id in traces
    assert traces[root.trace_id][0]["name"] == "query.orchid_view"
    (slow,) = tree.get("/tracing/slow_queries")
    assert slow["query"] == "Q"


# -- EXPLAIN ANALYZE end-to-end (acceptance criterion) ------------------------

@pytest.fixture(scope="module")
def flight_cluster(tmp_path_factory):
    from ytsaurus_tpu.client import connect
    from ytsaurus_tpu.schema import TableSchema

    client = connect(str(tmp_path_factory.mktemp("flight")))
    schema = TableSchema.make(
        [("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")],
        unique_keys=True)
    client.create("table", "//fr/t",
                  attributes={"schema": schema, "dynamic": True,
                              "pivot_keys": [[100], [200]]},
                  recursive=True)
    client.mount_table("//fr/t")
    client.insert_rows("//fr/t", [{"k": i, "g": i % 5, "v": i}
                                  for i in range(300)])
    return client


def test_explain_analyze_distributed_select(flight_cluster):
    client = flight_cluster
    profile = client.select_rows(
        "g, sum(v) AS s FROM [//fr/t] GROUP BY g", explain_analyze=True)
    assert [r["g"] for r in sorted(profile.rows,
                                   key=lambda r: r["g"])] == list(range(5))
    # Compile and execute reported SEPARATELY, both real.
    assert profile.compile_time >= 0.0
    assert profile.execute_time > 0.0
    assert profile.wall_time >= profile.execute_time
    assert profile.trace_id is not None
    tree = profile.span_tree()
    (root,) = tree
    assert root["name"] == "query.select"
    by_name = {}
    for node in _walk(tree):
        by_name.setdefault(node["name"], []).append(node)
    # Admission → coordinator shards → evaluator → tablet reads all
    # covered, in ONE trace.
    assert "serving.admission" in by_name
    shards = by_name["coordinator.shard"]
    assert shards       # ≥1 shard program (coalescing may merge tablets)
    assert all(isinstance(n["tags"]["shard"], int) for n in shards)
    assert all(n["tags"]["attempt"] == 0 for n in shards)
    evals = by_name["evaluator.run_plan"]
    assert all("fingerprint" in n["tags"] for n in evals)
    reads = by_name["tablet.read_snapshot"]
    assert all(n["tags"]["snapshot_cache"] in ("hit", "miss", "bypass")
               for n in reads)
    # Parent/child linkage: every non-root span's parent is in the trace.
    ids = {n["span_id"] for n in _walk(tree)}
    for node in _walk(tree):
        if node is not root:
            assert node["parent_span_id"] in ids
        assert node["trace_id"] == profile.trace_id
    # The same trace is retrievable by id from the /traces plane.
    from ytsaurus_tpu.server.monitoring import MonitoringServer
    from ytsaurus_tpu.server.orchid import OrchidTree
    from ytsaurus_tpu.utils.profiling import ProfilerRegistry
    server = MonitoringServer(OrchidTree(), ProfilerRegistry())
    server.start()
    try:
        detail = json.loads(urllib.request.urlopen(
            f"http://{server.address}/traces/{profile.trace_id}").read())
        assert detail["spans"][0]["name"] == "query.select"
    finally:
        server.stop()


def test_explain_analyze_compile_vs_cache_hit(flight_cluster):
    client = flight_cluster
    query = "g, count(*) AS c FROM [//fr/t] WHERE v < 250 GROUP BY g"
    first = client.select_rows(query, explain_analyze=True)
    again = client.select_rows(query, explain_analyze=True)
    # Warm plan cache: the second run compiles nothing new.
    assert again.statistics["compile_count"] == 0
    assert again.statistics["cache_hits"] >= 1
    assert again.compile_time == 0.0
    assert not _find(again.span_tree(), "evaluator.compile")
    assert first.statistics["compile_count"] >= 1 or \
        first.statistics["cache_hits"] >= 1


def test_unsampled_select_has_no_trace(flight_cluster, tracing_defaults):
    client = flight_cluster
    set_config = tracing_defaults
    set_config(yt_config.TracingConfig(sample_rate=0.0))
    before = len(get_collector().snapshot())
    rows = client.select_rows("k, v FROM [//fr/t] WHERE k < 3")
    assert len(rows) == 3
    new = get_collector().snapshot()[before:]
    assert [s for s in new if s.name == "query.select"] == []
    # explain_analyze still forces a full trace.
    profile = client.select_rows("k, v FROM [//fr/t] WHERE k < 3",
                                 explain_analyze=True)
    assert profile.trace_id is not None
    assert _find(profile.span_tree(), "query.select")


def test_traced_lookup_batches_link_into_caller_trace(flight_cluster):
    client = flight_cluster
    with TraceContext("test.lookup_root") as root:
        rows = client.lookup_rows("//fr/t", [(7,), (8,)])
    assert [r["k"] for r in rows] == [7, 8]
    spans = get_collector().find(root.trace_id)
    names = {s.name for s in spans}
    assert "query.lookup" in names
    assert "serving.batch_flush" in names
    # The flush span (flusher thread) parents into THIS caller's trace.
    flush = next(s for s in spans if s.name == "serving.batch_flush")
    assert flush.trace_id == root.trace_id
    assert "tablet.lookup" in names


# -- multi-hop: remote client → driver service → gateway → tablet -------------

def test_multihop_remote_driver_trace(flight_cluster):
    from ytsaurus_tpu.remote_client import connect_remote
    from ytsaurus_tpu.rpc import RpcServer
    from ytsaurus_tpu.server.services import DriverService

    client = flight_cluster
    server = RpcServer([DriverService(client)])
    server.start()
    remote = connect_remote(server.address)
    try:
        with TraceContext("cli.request") as root:
            result = remote.select_rows(
                "g, sum(v) AS s FROM [//fr/t] GROUP BY g",
                explain_analyze=True)
        def _text(v):
            return v.decode() if isinstance(v, bytes) else v
        result = {_text(k): v for k, v in dict(result).items()}
        trace_id = _text(result["trace_id"])
        # The whole hop chain shares the CLIENT's trace id.
        assert trace_id == root.trace_id
        spans = get_collector().find(root.trace_id)
        by_name = {}
        for s in spans:
            by_name.setdefault(s.name, []).append(s)
        # Client side: the RetryingChannel's per-attempt rpc.call span
        # sits between the root and the server-side handler span.
        (rpc_span,) = [s for s in by_name["rpc.call"]
                       if s.tags.get("method") == "execute"]
        assert rpc_span.parent_span_id == root.span_id
        assert rpc_span.tags["attempt"] == 0
        (server_span,) = by_name["driver.execute"]
        assert server_span.parent_span_id == rpc_span.span_id
        assert server_span.tags["service"] == "driver"
        (select_span,) = by_name["query.select"]
        assert select_span.parent_span_id == server_span.span_id
        shard_parents = {s.parent_span_id
                         for s in by_name["coordinator.shard"]}
        assert shard_parents == {select_span.span_id}
        assert "evaluator.run_plan" in by_name
        assert "tablet.read_snapshot" in by_name
        # The wire profile carries the compile/execute split too.
        assert float(result["execute_time"]) > 0.0
    finally:
        remote.close()
        server.stop()


# -- CLI ----------------------------------------------------------------------

def test_cli_explain_analyze_and_trace(flight_cluster, capsys):
    import re

    from ytsaurus_tpu import cli

    rc = cli.run(["select-rows", "--explain-analyze",
                  "g, count(*) AS c FROM [//fr/t] GROUP BY g"],
                 client=flight_cluster)
    out = capsys.readouterr().out
    assert rc == 0
    assert "compile" in out and "execute" in out and "spans:" in out
    assert "query.select" in out
    trace_id = re.search(r"trace_id: ([0-9a-f]{32})", out).group(1)

    rc = cli.run(["trace", trace_id], client=flight_cluster)
    out = capsys.readouterr().out
    assert rc == 0
    assert out.startswith(f"trace {trace_id}")
    assert "query.select" in out and "serving.admission" in out

    rc = cli.run(["trace", trace_id, "--json"], client=flight_cluster)
    tree = json.loads(capsys.readouterr().out)
    assert tree[0]["name"] == "query.select"

    rc = cli.run(["trace", "no-such-trace"], client=flight_cluster)
    assert rc == 1
    assert "no such trace" in capsys.readouterr().err


# -- threaded executors keep linkage ------------------------------------------

def test_scheduler_operation_spans(flight_cluster):
    """Operations plane: operation → phase → job spans link across the
    JobManager's worker threads (explicit contextvars capture)."""
    client = flight_cluster
    client.write_table("//fr/in", [{"a": i} for i in range(10)])
    collector = get_collector()
    before = len(collector.snapshot())
    op = client.run_map(
        lambda rows: [{"b": r["a"] * 2} for r in rows],
        "//fr/in", "//fr/out", rows_per_job=4)
    assert op.state == "completed"
    assert sorted(r["b"] for r in client.read_table("//fr/out")) == \
        [i * 2 for i in range(10)]
    new = collector.snapshot()[before:]
    ops = [s for s in new if s.name == "operation.run"]
    assert ops, "operation root span missing"
    op = ops[-1]
    phases = [s for s in new if s.name == "operation.phase"
              and s.trace_id == op.trace_id]
    assert phases
    jobs = [s for s in new if s.name == "operation.job"
            and s.trace_id == op.trace_id]
    assert jobs
    phase_ids = {s.span_id for s in phases}
    assert all(j.parent_span_id in phase_ids for j in jobs)
    assert {j.tags["index"] for j in jobs} <= set(range(len(jobs) + 16))
