"""QL end-to-end evaluation tests — the correctness oracle.

Modeled on the reference's ql_query_ut.cpp / ql_expressions_ut.cpp suites
(library/query/unittests): each case runs the full parse → typed IR → XLA
lowering → execute pipeline over in-memory columnar chunks.
"""

import pytest

from tests.harness import evaluate

T = "//t"


def _kv(rows):
    return {T: ([("k", "int64", "ascending"), ("v", "int64")], rows)}


KV6 = _kv([(i, i * 10) for i in range(6)])


# --- projection & arithmetic --------------------------------------------------

def test_select_star():
    evaluate(f"* FROM [{T}]", _kv([(1, 10), (2, 20)]),
             [{"k": 1, "v": 10}, {"k": 2, "v": 20}])


def test_project_arithmetic():
    evaluate(f"k + v AS s, k * 2 AS d FROM [{T}]", _kv([(1, 10), (2, 20)]),
             [{"s": 11, "d": 2}, {"s": 22, "d": 4}])


def test_integer_division_truncates():
    evaluate(f"k / 2 AS q, k % 3 AS r FROM [{T}]", _kv([(7, 0), (-7, 0)]),
             [{"q": 3, "r": 1}, {"q": -3, "r": -1}])


def test_division_by_zero_is_null():
    evaluate(f"k / v AS q FROM [{T}]", _kv([(6, 2), (5, 0)]),
             [{"q": 3}, {"q": None}])


def test_double_arithmetic_promotion():
    evaluate(f"k + 0.5 AS x FROM [{T}]", _kv([(1, 0)]), [{"x": 1.5}])


def test_unary_and_bitwise():
    evaluate(f"-k AS n, ~k AS b, k << 2 AS s FROM [{T}]", _kv([(5, 0)]),
             [{"n": -5, "b": -6, "s": 20}])


# --- filtering ----------------------------------------------------------------

def test_where_simple():
    evaluate(f"k FROM [{T}] WHERE k > 3", KV6,
             [{"k": 4}, {"k": 5}])


def test_where_and_or():
    evaluate(f"k FROM [{T}] WHERE k > 1 AND k < 4 OR k = 5", KV6,
             [{"k": 2}, {"k": 3}, {"k": 5}])


def test_where_in():
    evaluate(f"k FROM [{T}] WHERE k IN (1, 3, 5)", KV6,
             [{"k": 1}, {"k": 3}, {"k": 5}])


def test_where_between():
    evaluate(f"k FROM [{T}] WHERE k BETWEEN 2 AND 4", KV6,
             [{"k": 2}, {"k": 3}, {"k": 4}])


def test_where_not_between():
    evaluate(f"k FROM [{T}] WHERE k NOT BETWEEN 1 AND 4", KV6,
             [{"k": 0}, {"k": 5}])


def test_null_comparison_filters_out():
    rows = [(1, 10), (2, None), (3, 30)]
    evaluate(f"k FROM [{T}] WHERE v > 5", _kv(rows),
             [{"k": 1}, {"k": 3}])


def test_is_null_function():
    rows = [(1, 10), (2, None)]
    evaluate(f"k FROM [{T}] WHERE is_null(v)", _kv(rows), [{"k": 2}])
    evaluate(f"if_null(v, -1) AS w FROM [{T}]", _kv(rows),
             [{"w": 10}, {"w": -1}])


# --- group by / aggregates ----------------------------------------------------

GROUPED = {T: ([("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")],
               [(0, 0, 1), (1, 1, 2), (2, 0, 3), (3, 1, 4), (4, 0, 5)])}


def test_group_by_sum_count():
    evaluate(f"g, sum(v) AS s, count(v) AS c FROM [{T}] GROUP BY g", GROUPED,
             [{"g": 0, "s": 9, "c": 3}, {"g": 1, "s": 6, "c": 2}])


def test_group_by_min_max_avg():
    evaluate(f"g, min(v) AS lo, max(v) AS hi, avg(v) AS a FROM [{T}] GROUP BY g",
             GROUPED,
             [{"g": 0, "lo": 1, "hi": 5, "a": 3.0},
              {"g": 1, "lo": 2, "hi": 4, "a": 3.0}])


def test_group_by_expression_key():
    evaluate(f"k % 2 AS p, sum(v) AS s FROM [{T}] GROUP BY k % 2 AS p", GROUPED,
             [{"p": 0, "s": 9}, {"p": 1, "s": 6}])


def test_group_by_having():
    evaluate(f"g, sum(v) AS s FROM [{T}] GROUP BY g HAVING sum(v) > 8", GROUPED,
             [{"g": 0, "s": 9}])


def test_group_by_null_key_is_a_group():
    rows = [(1, 0, 5), (2, None, 7), (3, None, 1), (4, 0, 2)]
    evaluate(f"g, sum(v) AS s FROM [{T}] GROUP BY g",
             {T: ([("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")],
                  rows)},
             [{"g": 0, "s": 7}, {"g": None, "s": 8}])


def test_aggregate_nulls_skipped():
    rows = [(1, 0, None), (2, 0, 4), (3, 1, None)]
    evaluate(f"g, sum(v) AS s, count(v) AS c FROM [{T}] GROUP BY g",
             {T: ([("k", "int64", "ascending"), ("g", "int64"), ("v", "int64")],
                  rows)},
             [{"g": 0, "s": 4, "c": 1}, {"g": 1, "s": None, "c": 0}])


def test_total_aggregation_via_constant_key():
    evaluate(f"sum(v) AS s FROM [{T}] GROUP BY 1 AS one", GROUPED,
             [{"s": 15}])


def test_count_star():
    evaluate(f"g, count(*) AS c FROM [{T}] GROUP BY g", GROUPED,
             [{"g": 0, "c": 3}, {"g": 1, "c": 2}])


# --- order by / limit / offset ------------------------------------------------

def test_order_by_limit():
    evaluate(f"k FROM [{T}] ORDER BY k DESC LIMIT 3", KV6,
             [{"k": 5}, {"k": 4}, {"k": 3}], ordered=True)


def test_order_by_expression():
    evaluate(f"k FROM [{T}] ORDER BY v - 2 * k LIMIT 6",
             _kv([(0, 5), (1, 0), (2, 9)]),
             [{"k": 1}, {"k": 0}, {"k": 2}], ordered=True)


def test_order_by_nulls_first_asc():
    rows = [(1, 10), (2, None), (3, 5)]
    evaluate(f"v FROM [{T}] ORDER BY v LIMIT 10", _kv(rows),
             [{"v": None}, {"v": 5}, {"v": 10}], ordered=True)


def test_limit_without_order():
    out = evaluate(f"k FROM [{T}] LIMIT 2", KV6)
    assert len(out) == 2


def test_offset_limit():
    evaluate(f"k FROM [{T}] ORDER BY k LIMIT 2 OFFSET 2".replace(
        "LIMIT 2 OFFSET 2", "OFFSET 2 LIMIT 2"), KV6,
        [{"k": 2}, {"k": 3}], ordered=True)


# --- strings ------------------------------------------------------------------

STR_T = {T: ([("k", "int64", "ascending"), ("s", "string")],
             [(1, "apple"), (2, "banana"), (3, "cherry"), (4, None),
              (5, "apricot")])}


def test_string_equality_literal():
    evaluate(f"k FROM [{T}] WHERE s = 'banana'", STR_T, [{"k": 2}])


def test_string_inequality_range():
    evaluate(f"k FROM [{T}] WHERE s >= 'apple' AND s < 'b'", STR_T,
             [{"k": 1}, {"k": 5}])


def test_string_in():
    evaluate(f"k FROM [{T}] WHERE s IN ('apple', 'cherry', 'missing')", STR_T,
             [{"k": 1}, {"k": 3}])


def test_like():
    evaluate(f"k FROM [{T}] WHERE s LIKE 'ap%'", STR_T,
             [{"k": 1}, {"k": 5}])
    evaluate(f"k FROM [{T}] WHERE s LIKE '%an%'", STR_T, [{"k": 2}])
    evaluate(f"k FROM [{T}] WHERE s NOT LIKE 'ap%'", STR_T,
             [{"k": 2}, {"k": 3}])


def test_is_prefix_is_substr():
    evaluate(f"k FROM [{T}] WHERE is_prefix('ap', s)", STR_T,
             [{"k": 1}, {"k": 5}])
    evaluate(f"k FROM [{T}] WHERE is_substr('err', s)", STR_T, [{"k": 3}])


def test_lower_upper_length():
    evaluate("upper(s) AS u, length(s) AS l FROM [//t] WHERE k = 1", STR_T,
             [{"u": "APPLE", "l": 5}])


def test_string_projection_and_group():
    rows = [(1, "a"), (2, "b"), (3, "a"), (4, "b"), (5, "a")]
    evaluate(f"s, count(*) AS c FROM [{T}] GROUP BY s",
             {T: ([("k", "int64", "ascending"), ("s", "string")], rows)},
             [{"s": "a", "c": 3}, {"s": "b", "c": 2}])


def test_order_by_string():
    evaluate(f"s FROM [{T}] ORDER BY s DESC LIMIT 2", STR_T,
             [{"s": "cherry"}, {"s": "banana"}], ordered=True)


def test_min_max_string():
    evaluate(f"min(s) AS lo, max(s) AS hi FROM [{T}] GROUP BY 1 AS one", STR_T,
             [{"lo": "apple", "hi": "cherry"}])


# --- case / transform / if ----------------------------------------------------

def test_if_function():
    evaluate(f"if(k > 2, 'big', 'small') AS c FROM [{T}]", _kv([(1, 0), (5, 0)]),
             [{"c": "small"}, {"c": "big"}])


def test_case_expression():
    q = (f"CASE WHEN k < 2 THEN 'low' WHEN k < 4 THEN 'mid' ELSE 'high' END "
         f"AS c FROM [{T}]")
    evaluate(q, _kv([(1, 0), (3, 0), (5, 0)]),
             [{"c": "low"}, {"c": "mid"}, {"c": "high"}])


def test_case_with_operand():
    q = f"CASE k WHEN 1 THEN 10 WHEN 2 THEN 20 ELSE 0 END AS c FROM [{T}]"
    evaluate(q, _kv([(1, 0), (2, 0), (3, 0)]),
             [{"c": 10}, {"c": 20}, {"c": 0}])


def test_transform():
    q = f"transform(k, (1, 2), (10, 20), -1) AS t FROM [{T}]"
    evaluate(q, _kv([(1, 0), (2, 0), (9, 0)]),
             [{"t": 10}, {"t": 20}, {"t": -1}])


def test_transform_strings():
    q = f"transform(s, ('a', 'b'), ('x', 'y')) AS t FROM [{T}]"
    evaluate(q, {T: ([("k", "int64", "ascending"), ("s", "string")],
                     [(1, "a"), (2, "b"), (3, "c")])},
             [{"t": "x"}, {"t": "y"}, {"t": None}])


# --- joins --------------------------------------------------------------------

JOIN_TABLES = {
    T: ([("k", "int64", "ascending"), ("g", "int64")],
        [(1, 100), (2, 200), (3, 100), (4, 300)]),
    "//d": ([("g", "int64", "ascending"), ("name", "string")],
            [(100, "alpha"), (200, "beta"), (400, "gamma")]),
}


def test_inner_join_using():
    evaluate(f"k, name FROM [{T}] JOIN [//d] USING g", JOIN_TABLES,
             [{"k": 1, "name": "alpha"}, {"k": 2, "name": "beta"},
              {"k": 3, "name": "alpha"}])


def test_left_join_using():
    evaluate(f"k, name FROM [{T}] LEFT JOIN [//d] USING g", JOIN_TABLES,
             [{"k": 1, "name": "alpha"}, {"k": 2, "name": "beta"},
              {"k": 3, "name": "alpha"}, {"k": 4, "name": None}])


def test_join_on_expressions():
    evaluate(f"k, d.name AS n FROM [{T}] JOIN [//d] AS d ON g = d.g",
             JOIN_TABLES,
             [{"k": 1, "n": "alpha"}, {"k": 2, "n": "beta"},
              {"k": 3, "n": "alpha"}])


def test_join_then_group():
    evaluate(f"name, count(*) AS c FROM [{T}] JOIN [//d] USING g GROUP BY name",
             JOIN_TABLES,
             [{"name": "alpha", "c": 2}, {"name": "beta", "c": 1}])


def test_join_duplicate_foreign_rows():
    tables = {
        T: ([("k", "int64", "ascending"), ("g", "int64")], [(1, 7)]),
        "//d": ([("g", "int64", "ascending"), ("x", "int64")],
                [(7, 1), (7, 2)]),
    }
    # Non-unique foreign keys fan out.
    evaluate(f"k, x FROM [{T}] JOIN [//d] USING g", tables,
             [{"k": 1, "x": 1}, {"k": 1, "x": 2}])


# --- uint64 / double / boolean ------------------------------------------------

def test_uint64_literals_and_sum():
    rows = [(1, 2**63 + 1), (2, 2**63 + 2)]
    evaluate(f"sum(u) AS s FROM [{T}] GROUP BY 1 AS one",
             {T: ([("k", "int64", "ascending"), ("u", "uint64")], rows)},
             [{"s": 2**64 + 3 - 2**64}])  # wraps mod 2^64: (2^63+1)+(2^63+2)=2^64+3 → 3


def test_boolean_column_filter():
    rows = [(1, True), (2, False), (3, True)]
    evaluate(f"k FROM [{T}] WHERE b",
             {T: ([("k", "int64", "ascending"), ("b", "boolean")], rows)},
             [{"k": 1}, {"k": 3}])


def test_double_compare():
    rows = [(1, 0.5), (2, 1.5)]
    evaluate(f"k FROM [{T}] WHERE d > 1.0",
             {T: ([("k", "int64", "ascending"), ("d", "double")], rows)},
             [{"k": 2}])


# --- errors -------------------------------------------------------------------

def test_unknown_column_raises():
    from ytsaurus_tpu import YtError
    with pytest.raises(YtError):
        evaluate(f"zzz FROM [{T}]", KV6)


def test_type_mismatch_raises():
    from ytsaurus_tpu import YtError
    with pytest.raises(YtError):
        evaluate(f"k + s FROM [{T}]",
                 {T: ([("k", "int64", "ascending"), ("s", "string")],
                      [(1, "x")])})


def test_non_grouped_column_raises():
    from ytsaurus_tpu import YtError
    with pytest.raises(YtError):
        evaluate(f"v, sum(v) AS s FROM [{T}] GROUP BY g", GROUPED)


def test_parse_error():
    from ytsaurus_tpu import YtError
    with pytest.raises(YtError):
        evaluate(f"k FROM [{T}] WHERE ((", KV6)


# --- regression: review findings ---------------------------------------------

def test_multi_key_join():
    tables = {
        T: ([("a", "int64", "ascending"), ("b", "int64"), ("x", "int64")],
            [(1, 2, 10), (2, 1, 20), (1, 1, 30), (2, 2, 40), (3, 0, 50)]),
        "//d": ([("a", "int64", "ascending"), ("b", "int64"), ("y", "int64")],
                [(1, 1, 100), (1, 2, 200), (2, 1, 300), (2, 2, 400),
                 (3, 0, 500)]),
    }
    evaluate(f"x, y FROM [{T}] JOIN [//d] USING a, b", tables,
             [{"x": 10, "y": 200}, {"x": 20, "y": 300}, {"x": 30, "y": 100},
              {"x": 40, "y": 400}, {"x": 50, "y": 500}])


def test_predicate_suffix_precedence():
    # (k = 2 AND k IN (3)) OR v = 1 — OR must not be swallowed by IN's AND.
    evaluate(f"k FROM [{T}] WHERE k = 2 AND k IN (3) OR v = 1",
             _kv([(1, 1), (2, 10)]), [{"k": 1}])


def test_having_without_group_raises():
    from ytsaurus_tpu import YtError
    with pytest.raises(YtError):
        evaluate(f"k FROM [{T}] HAVING k > 1", KV6)


def test_multi_key_order_by():
    rows = [(1, 2, 10), (2, 1, 20), (3, 1, 5), (4, 2, 1)]
    evaluate("a, b FROM [//t] ORDER BY a, b DESC LIMIT 4",
             {T: ([("k", "int64", "ascending"), ("a", "int64"), ("b", "int64")],
                  [(k, a, b) for k, a, b in rows])},
             [{"a": 1, "b": 20}, {"a": 1, "b": 5}, {"a": 2, "b": 10},
              {"a": 2, "b": 1}], ordered=True)


def test_fast_group_order_by_with_literal_projection():
    # Fast-group path + ORDER BY + literal in projection (regression: stage
    # capacity mismatch after ordering).
    rows = [(1, "a", 2.0), (2, "b", 3.0), (3, "a", 5.0)]
    evaluate("s, sum(v) * 2 AS d FROM [//t] GROUP BY s ORDER BY s LIMIT 5",
             {T: ([("k", "int64", "ascending"), ("s", "string"),
                   ("v", "double")], rows)},
             [{"s": "a", "d": 14.0}, {"s": "b", "d": 6.0}], ordered=True)


def test_fast_group_cache_not_reused_across_vocab_shapes():
    # Two chunks, same plan + capacity, vocab sizes (1,2) vs (2,1): dims match
    # so the compile cache must key on per-key sizes (regression).
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.schema import TableSchema
    schema = TableSchema.make([("a", "string"), ("b", "string"),
                               ("v", "int64")])
    c1 = ColumnarChunk.from_rows(schema, [("x", "p", 1), ("x", "q", 2)])
    c2 = ColumnarChunk.from_rows(schema, [("y", "m", 5), ("z", "m", 7)])
    plan = build_query("a, b, sum(v) AS s FROM [//t] GROUP BY a, b",
                       {T: schema})
    ev = Evaluator()
    r1 = ev.run_plan(plan, c1).to_rows()
    r2 = ev.run_plan(plan, c2).to_rows()
    assert sorted((r["a"], r["b"], r["s"]) for r in r1) == \
        [(b"x", b"p", 1), (b"x", b"q", 2)]
    assert sorted((r["a"], r["b"], r["s"]) for r in r2) == \
        [(b"y", b"m", 5), (b"z", b"m", 7)]


def test_cardinality_exact_distinct():
    rows = [(1, 0, 5), (2, 0, 5), (3, 0, 7), (4, 1, None), (5, 1, 9),
            (6, 1, 9)]
    evaluate(f"g, cardinality(v) AS d FROM [{T}] GROUP BY g",
             {T: ([("k", "int64", "ascending"), ("g", "int64"),
                   ("v", "int64")], rows)},
             [{"g": 0, "d": 2}, {"g": 1, "d": 1}])


def test_with_totals():
    rows = evaluate(f"g, sum(v) AS s FROM [{T}] GROUP BY g WITH TOTALS",
                    GROUPED)
    regular = sorted((r["g"], r["s"]) for r in rows if r["g"] is not None)
    totals = [r for r in rows if r["g"] is None]
    assert regular == [(0, 9), (1, 6)]
    assert totals == [{"g": None, "s": 15}]


def test_with_totals_projected_expression():
    rows = evaluate(
        f"g + 100 AS gk, sum(v) * 2 AS d FROM [{T}] GROUP BY g WITH TOTALS",
        GROUPED)
    regular = sorted((r["gk"], r["d"]) for r in rows if r["gk"] is not None)
    totals = [r for r in rows if r["gk"] is None]
    assert regular == [(100, 18), (101, 12)]
    assert totals == [{"gk": None, "d": 30}]


def test_concat_and_float_predicates():
    rows = [(1, "foo", 1.5), (2, "bar", float("nan")),
            (3, None, float("inf"))]
    tables = {T: ([("k", "int64", "ascending"), ("s", "string"),
                   ("d", "double")], rows)}
    evaluate(f"concat(s, '-x') AS c FROM [{T}] WHERE k = 1", tables,
             [{"c": "foo-x"}])
    evaluate(f"concat('p:', s) AS c FROM [{T}] WHERE k = 2", tables,
             [{"c": "p:bar"}])
    evaluate(f"k FROM [{T}] WHERE is_nan(d)", tables, [{"k": 2}])
    evaluate(f"k FROM [{T}] WHERE NOT is_finite(d) AND NOT is_nan(d)",
             tables, [{"k": 3}])


def test_concat_two_columns():
    rows = [(1, "a", "x"), (2, "b", "y")]
    evaluate("concat(concat(s1, '/'), s2) AS c FROM [//t]",
             {T: ([("k", "int64", "ascending"), ("s1", "string"),
                   ("s2", "string")], rows)},
             [{"c": "a/x"}, {"c": "b/y"}])


def test_cardinality_nan_counts_once():
    rows = [(1, 0, float("nan")), (2, 0, float("nan")), (3, 0, 1.5),
            (4, 0, float("inf"))]
    evaluate(f"g, cardinality(d) AS c FROM [{T}] GROUP BY g",
             {T: ([("k", "int64", "ascending"), ("g", "int64"),
                   ("d", "double")], rows)},
             [{"g": 0, "c": 3}])  # nan, 1.5, inf — nans collapse


def test_cardinality_negative_zero_counts_once():
    rows = [(1, 0, 0.0), (2, 0, -0.0), (3, 0, 2.0)]
    evaluate(f"g, cardinality(d) AS c FROM [{T}] GROUP BY g",
             {T: ([("k", "int64", "ascending"), ("g", "int64"),
                   ("d", "double")], rows)},
             [{"g": 0, "c": 2}])


def test_topk_fast_path_with_nulls_desc_and_asc():
    # Large-capacity single-key ORDER BY LIMIT triggers the top_k candidate
    # path; null ordering must survive it (asc: nulls first, desc: last).
    import numpy as np
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.schema import TableSchema
    rng = np.random.default_rng(0)
    n = 20_000
    schema = TableSchema.make([("k", "int64"), ("v", "double")])
    valids = np.ones(n, dtype=bool)
    valids[:5] = False            # five null v rows
    chunk = ColumnarChunk.from_arrays(
        schema, {"k": np.arange(n), "v": rng.uniform(0, 1, n)},
        valids={"v": valids, "k": np.ones(n, dtype=bool)})
    from tests.harness import evaluate
    rows = evaluate("k, v FROM [//t] ORDER BY v LIMIT 8", {"//t": chunk})
    assert [r["v"] for r in rows[:5]] == [None] * 5       # nulls first (asc)
    vs = [r["v"] for r in rows[5:]]
    assert vs == sorted(vs)
    rows = evaluate("k, v FROM [//t] ORDER BY v DESC LIMIT 8", {"//t": chunk})
    assert all(r["v"] is not None for r in rows)
    vs = [r["v"] for r in rows]
    assert vs == sorted(vs, reverse=True)
    # oracle: exact top-8
    data = np.asarray(chunk.column("v").data[:n])[valids]
    assert abs(vs[0] - data.max()) < 1e-12


def test_int_key_dense_group_path_with_offset_range():
    # int64 keys in a narrow range far from zero take the dense path.
    import numpy as np
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.schema import TableSchema
    from tests.harness import evaluate
    rng = np.random.default_rng(1)
    n = 5000
    base = 7_000_000_000
    schema = TableSchema.make([("g", "int64"), ("v", "int64")])
    chunk = ColumnarChunk.from_arrays(
        schema, {"g": base + rng.integers(0, 100, n),
                 "v": rng.integers(0, 10, n)})
    rows = evaluate("g, sum(v) AS s, count(*) AS c FROM [//t] GROUP BY g",
                    {"//t": chunk})
    want = {}
    gs = np.asarray(chunk.column("g").data[:n])
    vs = np.asarray(chunk.column("v").data[:n])
    for g, v in zip(gs, vs):
        e = want.setdefault(int(g), [0, 0])
        e[0] += int(v)
        e[1] += 1
    assert len(rows) == len(want)
    for r in rows:
        assert want[r["g"]] == [r["s"], r["c"]]


def test_topk_desc_with_many_nulls_and_negatives():
    # Regression: null rows must not crowd out negative values in the
    # descending candidate selection, and fillers must be nulls (not
    # arbitrary rows) when values run out.
    import numpy as np
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.schema import TableSchema
    from tests.harness import evaluate
    n = 20_000
    schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    vals = -np.arange(2, n + 2)          # all negative
    valids = np.ones(n, dtype=bool)
    valids[:1000] = False                # 1000 nulls
    chunk = ColumnarChunk.from_arrays(
        schema, {"k": np.arange(n), "v": vals},
        valids={"v": valids, "k": np.ones(n, dtype=bool)})
    rows = evaluate("k, v FROM [//t] ORDER BY v DESC LIMIT 6", {"//t": chunk})
    got = [r["v"] for r in rows]
    assert got == [-1002, -1003, -1004, -1005, -1006, -1007]


def test_topk_value_at_type_extreme():
    # A valid row whose inverted key aliases the exclusion sentinel
    # (v = INT64_MAX ascending) must still be selectable.
    import numpy as np
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.schema import TableSchema
    from tests.harness import evaluate
    n = 20_000
    schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    vals = np.arange(n, dtype=np.int64) + 100
    vals[7] = np.iinfo(np.int64).max
    chunk = ColumnarChunk.from_arrays(schema, {"k": np.arange(n), "v": vals})
    rows = evaluate("k, v FROM [//t] ORDER BY v DESC LIMIT 3", {"//t": chunk})
    assert rows[0]["v"] == np.iinfo(np.int64).max
    rows = evaluate(
        "k FROM [//t] WHERE v >= 9223372036854775807 ORDER BY v LIMIT 5",
        {"//t": chunk})
    assert [r["k"] for r in rows] == [7]


def test_dense_group_uint64_high_range():
    import numpy as np
    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.schema import TableSchema
    from tests.harness import evaluate
    base = 2**63 + 5
    n = 4000
    rng = np.random.default_rng(0)
    offs = rng.integers(0, 50, n)
    schema = TableSchema.make([("g", "uint64"), ("v", "int64")])
    chunk = ColumnarChunk.from_arrays(
        schema, {"g": (np.full(n, base, dtype=np.uint64)
                       + offs.astype(np.uint64)),
                 "v": np.ones(n, dtype=np.int64)})
    rows = evaluate("g, count(*) AS c FROM [//t] GROUP BY g", {"//t": chunk})
    import collections
    want = collections.Counter((base + int(o)) for o in offs)
    assert len(rows) == len(want)
    for r in rows:
        assert want[r["g"]] == r["c"]


def test_timestamp_floor_functions():
    # Oracle: Python datetime over a spread of timestamps incl. pre-epoch.
    import datetime as dt
    stamps = [0, 1, 3599, 3600, 86399, 86400, 1_000_000_000,
              1_719_792_000, 951_782_400,          # 2000-02-29 leap day
              -1, -86401, -2_208_988_800]          # pre-epoch (1900)
    rows = [(i, s) for i, s in enumerate(stamps)]
    tables = {T: ([("k", "int64", "ascending"), ("ts", "int64")], rows)}
    out = evaluate(
        "k, timestamp_floor_hour(ts) AS h, timestamp_floor_day(ts) AS d, "
        "timestamp_floor_week(ts) AS w, timestamp_floor_month(ts) AS m, "
        "timestamp_floor_year(ts) AS y FROM [//t]", tables)
    for row, s in zip(sorted(out, key=lambda r: r["k"]), stamps):
        t = dt.datetime.fromtimestamp(s, dt.timezone.utc)
        def epoch(d):
            return int(dt.datetime(d.year, d.month, d.day,
                                   tzinfo=dt.timezone.utc).timestamp())
        assert row["h"] == s - (s % 3600), (s, row["h"])
        assert row["d"] == epoch(t), (s, row["d"])
        monday = t.date() - dt.timedelta(days=t.weekday())
        assert row["w"] == int(dt.datetime(
            monday.year, monday.month, monday.day,
            tzinfo=dt.timezone.utc).timestamp()), (s, row["w"])
        assert row["m"] == int(dt.datetime(
            t.year, t.month, 1, tzinfo=dt.timezone.utc).timestamp()), s
        assert row["y"] == int(dt.datetime(
            t.year, 1, 1, tzinfo=dt.timezone.utc).timestamp()), s


def test_timestamp_floor_in_group_by():
    rows = [(i, 86400 * (i // 3) + i) for i in range(9)]
    evaluate("timestamp_floor_day(ts) AS day, count(*) AS c FROM [//t] "
             "GROUP BY timestamp_floor_day(ts) AS day",
             {T: ([("k", "int64", "ascending"), ("ts", "int64")], rows)},
             [{"day": 0, "c": 3}, {"day": 86400, "c": 3},
              {"day": 172800, "c": 3}])


def test_argmin_argmax():
    rows = [(1, 0, "a", 5), (2, 0, "b", 2), (3, 0, "c", 9),
            (4, 1, "d", 7), (5, 1, "e", None), (6, 1, "f", 1)]
    tables = {T: ([("k", "int64", "ascending"), ("g", "int64"),
                   ("s", "string"), ("v", "int64")], rows)}
    evaluate(f"g, argmin(s, v) AS lo, argmax(s, v) AS hi FROM [{T}] GROUP BY g",
             tables,
             [{"g": 0, "lo": "b", "hi": "c"}, {"g": 1, "lo": "f", "hi": "d"}])


def test_argmax_nan_by_key_does_not_compete():
    rows = [(1, 0, "good", 5.0), (2, 0, "poison", float("nan")),
            (3, 0, "better", 7.0)]
    evaluate("g, argmax(s, d) AS top FROM [//t] GROUP BY g",
             {T: ([("k", "int64", "ascending"), ("g", "int64"),
                   ("s", "string"), ("d", "double")], rows)},
             [{"g": 0, "top": "better"}])


# --- null tuple elements in IN / BETWEEN / TRANSFORM --------------------------
# Reference semantics (CompareRowValues): null == null, null sorts first.

NULLABLE = {T: ([("k", "int64", "ascending"), ("v", "int64")],
                [(0, 0), (1, None), (2, 7), (3, None), (4, 1)])}


def test_in_null_element_matches_only_null_rows():
    # A null tuple element must NOT match v = 0 rows; it matches null rows.
    evaluate(f"k FROM [{T}] WHERE v IN (7, #)", NULLABLE,
             [{"k": 1}, {"k": 2}, {"k": 3}])


def test_in_null_only_tuple():
    evaluate(f"k FROM [{T}] WHERE v IN (#)", NULLABLE,
             [{"k": 1}, {"k": 3}])


def test_in_no_null_still_excludes_null_rows():
    evaluate(f"k FROM [{T}] WHERE v IN (0, 1)", NULLABLE,
             [{"k": 0}, {"k": 4}])


def test_in_string_null_element():
    rows = [(1, "a"), (2, None), (3, "b")]
    tables = {T: ([("k", "int64", "ascending"), ("s", "string")], rows)}
    evaluate(f"k FROM [{T}] WHERE s IN ('a', #)", tables,
             [{"k": 1}, {"k": 2}])


def test_between_null_lower_bound_matches_null_rows():
    # null sorts before every value: BETWEEN # AND 1 covers nulls, 0, 1.
    evaluate(f"k FROM [{T}] WHERE v BETWEEN # AND 1", NULLABLE,
             [{"k": 0}, {"k": 1}, {"k": 3}, {"k": 4}])


def test_transform_null_from_value():
    evaluate(f"k, transform(v, (7, #), (100, 200)) AS t FROM [{T}]",
             NULLABLE,
             [{"k": 0, "t": None}, {"k": 1, "t": 200}, {"k": 2, "t": 100},
              {"k": 3, "t": 200}, {"k": 4, "t": None}])
