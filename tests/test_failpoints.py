"""Deterministic failpoint subsystem (ISSUE 2 tentpole): registry,
schedule parsing, trigger arithmetic, modes, counters, leak guard."""

import time

import pytest

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils import failpoints


@pytest.fixture(autouse=True)
def _clean():
    failpoints.deactivate()
    yield
    failpoints.deactivate()


def _site(name, **kw):
    return failpoints.register_site(name, **kw)


def test_disabled_site_is_noop():
    site = _site("t.noop")
    before = site.hits
    for _ in range(100):
        site.hit()
    assert site.hits == before          # hits only count while active


def test_parse_spec_rules():
    rules = failpoints.parse_spec(
        "a.b=error:times=2;c.d=delay:ms=7:p=0.5;e.f=crash-once")
    assert rules["a.b"].mode == "error" and rules["a.b"].times == 2
    assert rules["c.d"].ms == 7.0 and rules["c.d"].p == 0.5
    assert rules["e.f"].mode == "crash-once"
    assert rules["e.f"].times == 1      # crash-once disarms itself
    with pytest.raises(YtError):
        failpoints.parse_spec("a.b=explode")
    with pytest.raises(YtError):
        failpoints.parse_spec("garbage")
    with pytest.raises(YtError):
        failpoints.parse_spec("a.b=error:wat=1")


def test_error_mode_times_and_counters():
    site = _site("t.err", error=lambda s: OSError(f"boom {s}"))
    h0, t0 = site.hits, site.triggers
    with failpoints.active("t.err=error:times=2"):
        with pytest.raises(OSError):
            site.hit()
        with pytest.raises(OSError):
            site.hit()
        site.hit()                      # budget exhausted: clean
        site.hit()
    assert site.hits - h0 == 4
    assert site.triggers - t0 == 2
    counters = failpoints.counters()["t.err"]
    assert counters["triggers"] >= 2


def test_after_and_one_in():
    site = _site("t.sched")
    fired = []
    with failpoints.active("t.sched=error:after=2:1in=3"):
        for i in range(11):
            try:
                site.hit()
            except YtError:
                fired.append(i)
    # Skips hits 0-1, then every 3rd eligible hit: 2, 5, 8.
    assert fired == [2, 5, 8]


def test_probability_deterministic_per_seed():
    site = _site("t.prob")

    def run(seed):
        out = []
        with failpoints.active("t.prob=error:p=0.5", seed=seed):
            for i in range(32):
                try:
                    site.hit()
                except YtError:
                    out.append(i)
        return out

    a, b = run(7), run(7)
    assert a == b                       # same seed → same schedule
    assert run(8) != a                  # and the seed actually matters
    assert 0 < len(a) < 32


def test_delay_mode_sleeps():
    site = _site("t.delay")
    with failpoints.active("t.delay=delay:ms=30:times=1"):
        t0 = time.monotonic()
        site.hit()
        assert time.monotonic() - t0 >= 0.02
        t0 = time.monotonic()
        site.hit()                      # disarmed: fast
        assert time.monotonic() - t0 < 0.02


def test_crash_once_pierces_except_exception():
    site = _site("t.crash")
    with failpoints.active("t.crash=crash-once"):
        with pytest.raises(failpoints.InjectedCrash):
            try:
                site.hit()
            except Exception:           # noqa: BLE001 — the point: a
                # simulated crash must NOT be caught by normal recovery.
                pytest.fail("InjectedCrash was caught by except Exception")
        site.hit()                      # once: disarmed


def test_torn_write_only_mangles_write_sites():
    site = _site("t.torn")
    with failpoints.active("t.torn=torn-write:times=1"):
        site.hit()                      # non-write probe: no-op
        assert site.triggers >= 0
        blob, torn = site.write_hit(b"x" * 100)
        assert torn and len(blob) == 50
        blob, torn = site.write_hit(b"x" * 100)
        assert not torn and len(blob) == 100


def test_nested_activation_restores_previous():
    site = _site("t.nest")
    with failpoints.active("t.nest=error:times=100"):
        with failpoints.active("other.site=delay"):
            site.hit()                  # outer schedule suspended
        with pytest.raises(YtError):
            site.hit()                  # outer schedule restored
    assert failpoints.active_spec() is None


def test_unknown_site_in_spec_is_allowed():
    with failpoints.active("never.imported.site=error"):
        _site("t.other").hit()          # unrelated site unaffected


def test_configure_from_config_object():
    from ytsaurus_tpu.config import FailpointsConfig
    site = _site("t.cfg")
    failpoints.configure(FailpointsConfig(spec="t.cfg=error:times=1",
                                          seed=3))
    try:
        with pytest.raises(YtError):
            site.hit()
    finally:
        failpoints.deactivate()
    failpoints.configure(FailpointsConfig())    # empty spec: no-op
    assert failpoints.active_spec() is None


def test_counters_exported_through_monitoring_endpoint():
    import json
    import urllib.request

    from ytsaurus_tpu.server.monitoring import MonitoringServer
    site = _site("t.mon")
    with failpoints.active("t.mon=error:times=1"):
        with pytest.raises(YtError):
            site.hit()
        srv = MonitoringServer()
        srv.start()
        try:
            body = urllib.request.urlopen(
                f"http://{srv.address}/failpoints", timeout=10).read()
            doc = json.loads(body)
            assert doc["active_spec"] == "t.mon=error:times=1"
            assert doc["sites"]["t.mon"]["triggers"] >= 1
            assert doc["schedule"]["t.mon"]["mode"] == "error"
            metrics = urllib.request.urlopen(
                f"http://{srv.address}/metrics", timeout=10).read().decode()
            assert 'failpoints_triggers{site="t.mon"}' in metrics
        finally:
            srv.stop()


def test_retry_policy_delay_shape():
    from ytsaurus_tpu.config import RetryPolicyConfig
    policy = RetryPolicyConfig(attempts=5, backoff=0.1, backoff_cap=0.3,
                               jitter=0.5)
    for attempt, cap in ((0, 0.1), (1, 0.2), (2, 0.3), (5, 0.3)):
        for _ in range(8):
            d = policy.delay(attempt)
            assert cap * 0.5 <= d <= cap    # jitter only shrinks
    none = RetryPolicyConfig(attempts=1, backoff=0.1, jitter=0.0)
    assert none.delay(0) == 0.1


def test_state_write_failpoint_quorum_rides_out_one_failed_put(tmp_path):
    """`server.state.write` (ISSUE 9 satellite) injects a disk fault
    into a data node's durable snapshot publish; the quorum ladder in
    QuorumWal.store_snapshot must ride out ONE failed replica put and
    fetch_snapshot must still serve the blob from a surviving node."""
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.cypress.quorum import QuorumWal
    from ytsaurus_tpu.rpc.channel import Channel
    from ytsaurus_tpu.rpc.server import RpcServer
    from ytsaurus_tpu.server.services import DataNodeService

    servers = []
    channels = []
    try:
        for i in range(2):
            service = DataNodeService(
                FsChunkStore(str(tmp_path / f"n{i}" / "chunks")),
                str(tmp_path / f"n{i}" / "j"))
            server = RpcServer([service], port=0)
            server.start()
            servers.append(server)
            channels.append(Channel(f"127.0.0.1:{server.port}",
                                    timeout=20))
        wal = QuorumWal(str(tmp_path / "local.wal"), "j0", channels,
                        quorum=2)
        with failpoints.active("server.state.write=error:times=1",
                               seed=5):
            wal.store_snapshot(7, b"state-blob")   # one put injected
        assert failpoints.counters()["server.state.write"][
            "triggers"] == 1
        assert wal.fetch_snapshot() == (7, b"state-blob")
        # Both puts failing breaches the quorum: the ladder refuses
        # loudly instead of pretending the snapshot is durable.
        with failpoints.active("server.state.write=error:times=2",
                               seed=5):
            with pytest.raises(YtError):
                wal.store_snapshot(8, b"lost-blob")
        wal.close()
    finally:
        for channel in channels:
            channel.close()
        for server in servers:
            server.stop()
