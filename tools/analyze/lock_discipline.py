"""Lock-discipline + lock-ordering pass (`yt analyze --pass locks`).

Annotation convention: the comment `# guards: attr_a, attr_b` on a lock
assignment declares what state that lock protects —

    self._lock = threading.Lock()   # guards: _usage, _records
    _LOCK = threading.Lock()        # guards: _STATE, _SITES

(`@guarded_by` spelled as a comment works too: `# guarded_by: _lock` on
a state attribute's own assignment line inverts the declaration.)

Rules
-----
  lock-guard       annotated state mutated outside a `with <lock>` scope
                   (methods named `*_locked` are exempt by convention —
                   they document "caller holds the lock").
  lock-order       the GLOBAL lock-acquisition-order graph (edges from
                   nested `with` scopes, propagated one call level deep
                   through same-file calls and the registered singleton
                   accessors) contains a cycle — a potential deadlock.
  lock-annotation  a `# guards:` comment that names state the class
                   never defines, or is not attached to an assignment
                   (typo protection: a misspelled guard silently checks
                   nothing).

Only files carrying at least one annotation are checked for lock-guard
(opt-in by annotation); the order graph spans every annotated lock in
the tree.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from tools.analyze.core import (
    Finding,
    SourceFile,
    dotted_name,
    walk_functions,
)

PASS_NAME = "locks"

_GUARDS_RE = re.compile(r"#\s*guards:\s*([A-Za-z0-9_,\s]+?)\s*$")
_GUARDED_BY_RE = re.compile(r"#\s*guarded_by:\s*([A-Za-z0-9_]+)\s*$")

# Mutating method names on containers/objects — calling one on guarded
# state is a write for discipline purposes.
MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "popleft", "remove", "discard", "clear",
    "appendleft", "move_to_end",
}

# Singleton accessors: `get_x().method()` inside a lock scope acquires
# whatever `method` acquires on the returned class.  (path, ClassName)
# per accessor; paths are repo-relative.
ACCESSORS = {
    "get_accountant": ("ytsaurus_tpu/query/accounting.py",
                       "ResourceAccountant"),
    "get_workload_log": ("ytsaurus_tpu/query/workload.py", "WorkloadLog"),
    "get_collector": ("ytsaurus_tpu/utils/tracing.py", "SpanCollector"),
    "get_history": ("ytsaurus_tpu/utils/profiling.py", "MetricsHistory"),
    "get_slo_tracker": ("ytsaurus_tpu/utils/slo.py", "SloTracker"),
    "get_compile_observatory": ("ytsaurus_tpu/query/engine/evaluator.py",
                                "CompileObservatory"),
}


class LockInfo:
    """One annotated lock: identity + the state names it guards."""

    __slots__ = ("path", "cls", "attr", "guards", "line")

    def __init__(self, path: str, cls: Optional[str], attr: str,
                 guards: "set[str]", line: int):
        self.path = path
        self.cls = cls          # None for module-level locks
        self.attr = attr
        self.guards = guards
        self.line = line

    @property
    def node_id(self) -> str:
        scope = f"{self.cls}." if self.cls else ""
        return f"{self.path}::{scope}{self.attr}"


def _annotation_lines(f: SourceFile):
    for lineno, text in enumerate(f.lines, start=1):
        match = _GUARDS_RE.search(text)
        if match:
            yield lineno, "guards", [s.strip() for s in
                                     match.group(1).split(",") if s.strip()]
            continue
        match = _GUARDED_BY_RE.search(text)
        if match:
            yield lineno, "guarded_by", [match.group(1)]


def _assign_target_name(stmt: ast.stmt) -> "tuple[Optional[str], bool]":
    """(name, is_self_attr) for a single-target simple assignment."""
    target = None
    if isinstance(stmt, (ast.Assign,)) and len(stmt.targets) == 1:
        target = stmt.targets[0]
    elif isinstance(stmt, ast.AnnAssign):
        target = stmt.target
    if isinstance(target, ast.Name):
        return target.id, False
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and \
            target.value.id == "self":
        return target.attr, True
    return None, False


def collect_locks(f: SourceFile) -> "tuple[list[LockInfo], list[Finding]]":
    """Parse a file's `# guards:` / `# guarded_by:` annotations into
    LockInfos, with lock-annotation findings for detached/typo'd ones."""
    findings: list[Finding] = []
    # lineno -> (owning class name or None) for every assignment stmt.
    stmts: dict[int, tuple[Optional[str], ast.stmt]] = {}
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ClassDef):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    stmts.setdefault(sub.lineno, (node.name, sub))
    for node in ast.walk(f.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            stmts.setdefault(node.lineno, (None, node))

    locks: dict[tuple, LockInfo] = {}
    deferred: list[tuple[int, str, str]] = []   # guarded_by: resolve late
    for lineno, kind, names in _annotation_lines(f):
        owner = stmts.get(lineno)
        if owner is None and f.lines[lineno - 1].lstrip().startswith("#"):
            # Standalone comment: governs the assignment directly below.
            owner = stmts.get(lineno + 1)
        if owner is None:
            findings.append(Finding(
                PASS_NAME, "lock-annotation", f.path, lineno,
                f"`# {kind}:` annotation is not attached to an "
                f"assignment statement"))
            continue
        cls, stmt = owner
        name, _is_self = _assign_target_name(stmt)
        if name is None:
            findings.append(Finding(
                PASS_NAME, "lock-annotation", f.path, lineno,
                f"`# {kind}:` annotation on an unsupported assignment "
                f"shape (need `self.x = ...` or `NAME = ...`)"))
            continue
        if kind == "guards":
            key = (cls, name)
            info = locks.get(key)
            if info is None:
                info = locks[key] = LockInfo(f.path, cls, name, set(),
                                             lineno)
            info.guards.update(names)
        else:                                   # guarded_by on state
            deferred.append((lineno, cls, name, names[0]))
    for lineno, cls, state_name, lock_name in deferred:
        key = (cls, lock_name)
        info = locks.get(key)
        if info is None:
            info = locks[key] = LockInfo(f.path, cls, lock_name, set(),
                                         lineno)
        info.guards.add(state_name)

    # Typo protection: every guarded name must exist as state in scope.
    for info in locks.values():
        present: set[str] = set()
        if info.cls is not None:
            cls_node = next((n for n in ast.walk(f.tree)
                             if isinstance(n, ast.ClassDef)
                             and n.name == info.cls), None)
            if cls_node is not None:
                for node in ast.walk(cls_node):
                    if isinstance(node, ast.Attribute) and \
                            isinstance(node.value, ast.Name) and \
                            node.value.id == "self":
                        present.add(node.attr)
        else:
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Name):
                    present.add(node.id)
        for guard in sorted(info.guards - present):
            findings.append(Finding(
                PASS_NAME, "lock-annotation", f.path, info.line,
                f"lock {info.attr!r} declares guard {guard!r} but "
                f"{'class ' + info.cls if info.cls else 'the module'} "
                f"never references it (typo?)"))
    return list(locks.values()), findings


def _with_lock_attrs(item: ast.withitem, cls_locks: "set[str]",
                     mod_locks: "set[str]") -> Optional[str]:
    """The annotated lock a `with` item acquires, or None."""
    expr = item.context_expr
    name = dotted_name(expr)
    if name.startswith("self.") and name[5:] in cls_locks:
        return name[5:]
    if name in mod_locks:
        return name
    return None


class _Mutation:
    __slots__ = ("name", "is_self", "line", "verb")

    def __init__(self, name, is_self, line, verb):
        self.name = name
        self.is_self = is_self
        self.line = line
        self.verb = verb


def _node_mutations(node: ast.AST):
    """Mutations attributable to THIS node alone (no recursion):
    assignment/augassign/del of `self.x` / `x` (incl. subscripts), or a
    mutator-method call on one.  The scope walker visits every node, so
    per-node attribution covers mutator calls buried anywhere (return
    values, branch conditions, comprehensions) without double counting."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = [(t, "assigned") for t in node.targets]
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [(node.target, "assigned")]
    elif isinstance(node, ast.Delete):
        targets = [(t, "deleted") for t in node.targets]
    elif isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATORS:
            targets = [(fn.value, f"mutated via .{fn.attr}()")]
    for target, verb in targets:
        while isinstance(target, ast.Subscript):
            target = target.value
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            yield _Mutation(target.attr, True, node.lineno, verb)
        elif isinstance(target, ast.Name):
            yield _Mutation(target.id, False, node.lineno, verb)


def _mutations(node: ast.AST):
    """Every state mutation anywhere in a subtree."""
    for child in ast.walk(node):
        yield from _node_mutations(child)


def _check_function(f: SourceFile, cls: Optional[str],
                    fn: ast.AST, locks: "list[LockInfo]",
                    findings: "list[Finding]") -> None:
    cls_lock_attrs = {l.attr for l in locks if l.cls == cls}
    mod_lock_names = {l.attr for l in locks if l.cls is None}
    guard_map: dict[tuple[str, bool], list[LockInfo]] = {}
    for lock in locks:
        for guarded in lock.guards:
            if lock.cls is None:
                guard_map.setdefault((guarded, False), []).append(lock)
            elif lock.cls == cls:
                guard_map.setdefault((guarded, True), []).append(lock)
    if not guard_map:
        return

    held: list[str] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return      # nested defs: separate dynamic scope, skip
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = [
                a for a in (_with_lock_attrs(i, cls_lock_attrs,
                                             mod_lock_names)
                            for i in node.items) if a is not None]
            held.extend(acquired)
            for stmt in node.body:
                visit(stmt)
            del held[len(held) - len(acquired):len(held)]
            # with-item expressions themselves can contain mutations
            # (their subtrees are NOT re-visited below).
            for item in node.items:
                check(_mutations(item.context_expr))
            return
        # One node's OWN mutations only — children are visited next, so
        # mutator calls buried in return/if/for heads are still reached
        # (their Call node is visited itself), without double-counting.
        check(_node_mutations(node))
        for child in ast.iter_child_nodes(node):
            visit(child)

    def check(mutations) -> None:
        for mut in mutations:
            for lock in guard_map.get((mut.name, mut.is_self), ()):
                if lock.attr in held:
                    continue
                if f.waived("lock-guard", mut.line):
                    continue
                owner = "self." if mut.is_self else ""
                findings.append(Finding(
                    PASS_NAME, "lock-guard", f.path, mut.line,
                    f"{owner}{mut.name} is {mut.verb} outside "
                    f"`with {'self.' if lock.cls else ''}{lock.attr}` "
                    f"(declared `# guards:` at "
                    f"{f.path}:{lock.line})"))

    for stmt in fn.body:
        visit(stmt)


def check_discipline(f: SourceFile, locks: "list[LockInfo]",
                     findings: "list[Finding]") -> None:
    for cls, fn in walk_functions(f.tree):
        if fn.name == "__init__" or fn.name.endswith("_locked"):
            # Construction races with nobody; `_locked` names document
            # "caller already holds the lock".
            continue
        if f.function_waived("lock-guard", fn):
            continue
        _check_function(f, cls, fn, locks, findings)


# -- lock-acquisition-order graph ----------------------------------------------


def _resolve_callee(call: ast.Call, path: str,
                    cls: Optional[str]) -> "Optional[tuple]":
    """(path, cls, method) key of a call target we can resolve: a
    self-method, a same-file module function, or a registered singleton
    accessor (`get_x().method(...)`)."""
    fnode = call.func
    if isinstance(fnode, ast.Attribute) and \
            isinstance(fnode.value, ast.Call):
        target = ACCESSORS.get(dotted_name(fnode.value.func))
        if target is not None:
            return (target[0], target[1], fnode.attr)
    name = dotted_name(fnode)
    if name.startswith("self.") and "." not in name[5:]:
        return (path, cls, name[5:])
    if name and "." not in name:
        return (path, None, name)
    return None


def _resolve_callees(call: ast.Call, path: str, cls: Optional[str],
                     method_index=None, fn_index=None,
                     ctor_index=None) -> "list[tuple]":
    """All plausible call targets.  The precise resolution above, plus —
    when the reconciliation indexes are supplied (guard_inference's
    superset graph) — tree-wide METHOD-NAME resolution into lock-bearing
    classes: `self.hits_n.increment()` maps to every lock-bearing class
    defining `increment` (over-approximation is sound for a superset
    graph; the precise cycle-checked graph never passes indexes)."""
    precise = _resolve_callee(call, path, cls)
    out = [precise] if precise is not None else []
    if method_index is None and fn_index is None:
        return out
    fnode = call.func
    name = dotted_name(fnode)
    if method_index and isinstance(fnode, ast.Attribute) and \
            precise is None:
        for tpath, tcls in method_index.get(fnode.attr, ()):
            out.append((tpath, tcls, fnode.attr))
    if fn_index:
        # Always consulted: a bare `get_accountant()` resolves same-file
        # by the precise rule even when no such function exists there —
        # the cross-file candidates must still be considered.
        last = name.rsplit(".", 1)[-1]
        for tpath, _tcls in fn_index.get(last, ()):
            key = (tpath, None, last)
            if key not in out:
                out.append(key)
    if ctor_index:
        # Constructor calls: `WorkloadLog(...)` runs __init__ (which may
        # create sensors and take the registry lock).
        last = name.rsplit(".", 1)[-1]
        for tpath, tcls in ctor_index.get(last, ()):
            key = (tpath, tcls, "__init__")
            if key not in out:
                out.append(key)
    return out


def _direct_acquisitions(fn: ast.AST, cls_locks: "set[str]",
                         mod_locks: "set[str]"):
    """(lock_attr, line) for every with-acquisition anywhere in fn."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                attr = _with_lock_attrs(item, cls_locks, mod_locks)
                if attr is not None:
                    yield attr, node.lineno


def build_order_graph(files: "list[SourceFile]",
                      locks_by_file: "dict[str, list[LockInfo]]",
                      method_index=None, fn_index=None,
                      ctor_index=None):
    """Edges A→B: lock B acquired while A is held — from syntactic
    nesting, plus call propagation (self-methods and module functions
    in the same file, and the ACCESSORS singletons; guard_inference's
    reconciliation graph additionally passes tree-wide name indexes for
    a deeper, over-approximate closure)."""
    # (path, cls, fn_name) -> [(lock_node_id, line)]; closure over
    # same-class self-calls so `get_x().outer()` sees inner locks too.
    fn_locks: dict[tuple, list] = {}
    fn_calls: dict[tuple, list] = {}
    for f in files:
        locks = locks_by_file.get(f.path, [])
        for cls, fn in walk_functions(f.tree):
            cls_lock_attrs = {l.attr for l in locks if l.cls == cls}
            mod_lock_names = {l.attr for l in locks if l.cls is None}
            key = (f.path, cls, fn.name)
            acquired = []
            for attr, line in _direct_acquisitions(
                    fn, cls_lock_attrs, mod_lock_names):
                lock = next(l for l in locks
                            if l.attr == attr and
                            (l.cls == cls or l.cls is None))
                acquired.append((lock.node_id, line))
            fn_locks[key] = acquired
            fn_calls[key] = [
                callee
                for c in ast.walk(fn) if isinstance(c, ast.Call)
                for callee in _resolve_callees(c, f.path, cls,
                                               method_index, fn_index,
                                               ctor_index)]

    # Fixpoint: a function's lock set includes its callees' (bounded —
    # convergence breaks out early; the bound only caps pathology).
    closure: dict[tuple, set] = {k: {l for l, _ in v}
                                 for k, v in fn_locks.items()}
    for _ in range(16):
        changed = False
        for key, calls in fn_calls.items():
            mine = closure[key]
            before = len(mine)
            for callee in calls:
                mine |= closure.get(callee, set())
            changed |= len(mine) != before
        if not changed:
            break

    edges: dict[tuple, tuple] = {}    # (A, B) -> (path, line)
    for f in files:
        locks = locks_by_file.get(f.path, [])
        for cls, fn in walk_functions(f.tree):
            cls_lock_attrs = {l.attr for l in locks if l.cls == cls}
            mod_lock_names = {l.attr for l in locks if l.cls is None}

            def lock_id(attr: str) -> str:
                return next(l.node_id for l in locks
                            if l.attr == attr and
                            (l.cls == cls or l.cls is None))

            held: list[str] = []

            def visit(node: ast.AST) -> None:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)) \
                        and node is not fn:
                    return
                acquired: list[str] = []
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        attr = _with_lock_attrs(item, cls_lock_attrs,
                                                mod_lock_names)
                        if attr is None:
                            continue
                        nid = lock_id(attr)
                        for h in held:
                            if h != nid:
                                edges.setdefault((h, nid),
                                                 (f.path, node.lineno))
                        acquired.append(nid)
                        held.append(nid)
                elif isinstance(node, ast.Call) and held:
                    for callee in _resolve_callees(node, f.path, cls,
                                                   method_index,
                                                   fn_index, ctor_index):
                        for nid in closure.get(callee, ()):
                            for h in held:
                                if h != nid:
                                    edges.setdefault(
                                        (h, nid), (f.path, node.lineno))
                for child in ast.iter_child_nodes(node):
                    visit(child)
                del held[len(held) - len(acquired):len(held)]

            visit(fn)
    return edges


def find_cycles(edges: "dict[tuple, tuple]") -> "list[list[str]]":
    graph: dict[str, list[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    cycles: list[list[str]] = []
    seen_cycles: set = set()
    color: dict[str, int] = {}
    stack: list[str] = []

    def dfs(node: str) -> None:
        color[node] = 1
        stack.append(node)
        for nxt in graph[node]:
            if color.get(nxt, 0) == 0:
                dfs(nxt)
            elif color.get(nxt) == 1:
                cycle = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cycle)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cycle)
        stack.pop()
        color[node] = 2

    for node in sorted(graph):
        if color.get(node, 0) == 0:
            dfs(node)
    return cycles


def order_graph_snapshot(files: "list[SourceFile]") -> dict:
    """The acquisition-order graph as plain data (`yt analyze` --json
    consumers + tests)."""
    locks_by_file: dict[str, list[LockInfo]] = {}
    for f in files:
        locks, _ = collect_locks(f)
        if locks:
            locks_by_file[f.path] = locks
    edges = build_order_graph(files, locks_by_file)
    return {
        "locks": sorted(l.node_id for ls in locks_by_file.values()
                        for l in ls),
        "edges": sorted([a, b, f"{p}:{line}"]
                        for (a, b), (p, line) in edges.items()),
        "cycles": find_cycles(edges),
    }


def run(files: "list[SourceFile]") -> "list[Finding]":
    findings: list[Finding] = []
    locks_by_file: dict[str, list[LockInfo]] = {}
    for f in files:
        locks, annotation_findings = collect_locks(f)
        findings.extend(annotation_findings)
        if locks:
            locks_by_file[f.path] = locks
            check_discipline(f, locks, findings)
    edges = build_order_graph(files, locks_by_file)
    for cycle in find_cycles(edges):
        first_edge = (cycle[0], cycle[1])
        path, line = edges.get(first_edge, (cycle[0].split("::")[0], 1))
        findings.append(Finding(
            PASS_NAME, "lock-order", path, line,
            "lock-acquisition-order cycle (potential deadlock): "
            + " -> ".join(cycle)))
    return findings
