"""ANSI/ClickHouse-flavored SQL over YT tables — the CHYT analog.

Ref mapping (yt/chyt):
  CHYT accepts ClickHouse SQL over YT tables     → translate_sql rewrites
  (`SELECT ... FROM "//path"`), converting          the dialect onto the
  schemas/blocks into the CH engine                 native QL engine (the
  (chyt/server/conversion.h)                        columnar XLA backend
                                                    IS the vectorized
                                                    engine here, so no
                                                    second execution
                                                    engine is embedded)
  query dispatch via Query Tracker engines       → registered as engine
  (server/query_tracker/chyt_engine.cpp)           "chyt" / alias "sql"

Dialect deltas handled:
  SELECT * / SELECT cols FROM "//path" | `//path` | [//path]
  ANSI double-quoted / backticked identifiers → bare identifiers
  <>  → !=            (inequality)
  CH aggregate names  → native (uniq/uniqExact → cardinality, any → first)
  LIMIT n OFFSET m    → OFFSET m LIMIT n (QL clause order)
Strings must use single quotes (ANSI); double quotes always mean
identifiers, exactly like ClickHouse's default dialect.
"""

from __future__ import annotations

import re

from ytsaurus_tpu.errors import EErrorCode, YtError

_TOKEN = re.compile(r"""
    (?P<ws>\s+)
  | (?P<string>'(?:\\.|[^'\\])*')
  | (?P<dquote>"(?:[^"\\]|\\.)*")
  | (?P<btick>`[^`]*`)
  | (?P<bracket>\[[^\]]*\])
  | (?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?u?)
  | (?P<word>[A-Za-z_][A-Za-z0-9_.]*)
  | (?P<op><>|<=|>=|!=|\|\||[-+*/%(),=<>.])
""", re.VERBOSE)

_AGG_RENAMES = {
    "uniq": "cardinality",
    "uniqexact": "cardinality",
    "any": "first",
}

_TABLE_KEYWORDS = {"from", "join"}


def _tokens(text: str):
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            raise YtError(f"SQL: cannot tokenize at {text[pos:pos + 20]!r}",
                          code=EErrorCode.QueryParseError)
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        yield kind, m.group()


def translate_sql(sql: str) -> str:
    """ClickHouse/ANSI-flavored SELECT → native QL text."""
    out: list[str] = []
    expecting_table = False
    limit_value = None
    offset_value = None
    state = "normal"
    for kind, tok in _tokens(sql.strip().rstrip(";")):
        low = tok.lower()
        if state == "limit" and kind == "num":
            limit_value = tok
            state = "normal"
            continue
        if state == "offset" and kind == "num":
            offset_value = tok
            state = "normal"
            continue
        if kind == "word" and low == "limit":
            state = "limit"
            continue
        if kind == "word" and low == "offset":
            state = "offset"
            continue
        if expecting_table:
            out.append(_table_ref(kind, tok))
            expecting_table = False
            continue
        if kind == "word" and low in _TABLE_KEYWORDS:
            out.append(tok)
            expecting_table = True
            continue
        if kind == "dquote":
            # ANSI: double quotes are identifiers.
            out.append(tok[1:-1])
            continue
        if kind == "btick":
            out.append(tok[1:-1])
            continue
        if kind == "op" and tok == "<>":
            out.append("!=")
            continue
        if kind == "word" and low in _AGG_RENAMES:
            out.append(_AGG_RENAMES[low])
            continue
        out.append(tok)
    ql = _respace(out)
    if ql.lower().startswith("select "):
        ql = ql[len("select "):]
    # QL clause order: ... OFFSET m LIMIT n.
    if offset_value is not None:
        ql += f" OFFSET {offset_value}"
    if limit_value is not None:
        ql += f" LIMIT {limit_value}"
    return ql


def _table_ref(kind: str, tok: str) -> str:
    if kind == "bracket":
        return tok                       # already QL form
    if kind == "dquote" or kind == "btick":
        return f"[{tok[1:-1]}]"
    if kind == "word":
        # Bare identifier: treat as an absolute cypress path component
        # under the root ("FROM my_table" → [//my_table], matching CHYT's
        # default-database-as-directory mapping).
        path = tok if tok.startswith("//") else f"//{tok}"
        return f"[{path}]"
    if kind == "string":
        return f"[{tok[1:-1]}]"
    raise YtError(f"SQL: bad table reference {tok!r}",
                  code=EErrorCode.QueryParseError)


_NO_SPACE_BEFORE = {",", ")", "."}
_NO_SPACE_AFTER = {"(", "."}


def _respace(tokens: "list[str]") -> str:
    parts: list[str] = []
    prev = ""
    for tok in tokens:
        if parts and tok not in _NO_SPACE_BEFORE and \
                prev not in _NO_SPACE_AFTER:
            parts.append(" ")
        parts.append(tok)
        prev = tok
    return "".join(parts)


def execute_sql(client, sql: str) -> "list[dict]":
    return client.select_rows(translate_sql(sql))


def register() -> None:
    from ytsaurus_tpu.server.query_tracker import register_engine
    register_engine("chyt", execute_sql)
    register_engine("sql", execute_sql)


register()
