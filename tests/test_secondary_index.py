"""Secondary indexes: maintenance on write, query rewrite, backfill.

Ref model: library/query/secondary_index + index-table maintenance in the
tablet write path.
"""

import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.schema import TableSchema

SCHEMA = TableSchema.make([
    ("id", "int64", "ascending"), ("city", "string"), ("score", "int64")],
    unique_keys=True)


@pytest.fixture
def client(tmp_path):
    c = connect(str(tmp_path))
    c.create("table", "//users", recursive=True,
             attributes={"schema": SCHEMA, "dynamic": True})
    c.mount_table("//users")
    return c


def test_backfill_and_query_rewrite(client):
    client.insert_rows("//users", [
        {"id": 1, "city": "spb", "score": 10},
        {"id": 2, "city": "msk", "score": 20},
        {"id": 3, "city": "spb", "score": 30}])
    client.create_secondary_index("//users", "//users_by_city", ["city"])
    # Index table backfilled with (city, id) keys.
    assert client.select_rows(
        "city, id FROM [//users_by_city]") == [
        {"city": b"msk", "id": 2},
        {"city": b"spb", "id": 1}, {"city": b"spb", "id": 3}]
    # Query on the indexed column serves via the index.
    rows = client.select_rows(
        "id, score FROM [//users] WHERE city = 'spb'")
    assert rows == [{"id": 1, "score": 10}, {"id": 3, "score": 30}]


def test_index_maintained_on_writes(client):
    client.create_secondary_index("//users", "//by_city", ["city"])
    client.insert_rows("//users", [{"id": 1, "city": "spb", "score": 1}])
    # Move the row to a new city: the stale entry must disappear.
    client.insert_rows("//users", [{"id": 1, "city": "msk", "score": 2}])
    assert client.select_rows("city, id FROM [//by_city]") == [
        {"city": b"msk", "id": 1}]
    assert client.select_rows(
        "id FROM [//users] WHERE city = 'spb'") == []
    assert client.select_rows(
        "id FROM [//users] WHERE city = 'msk'") == [{"id": 1}]
    # Partial (update-mode) write that does not touch the indexed column
    # keeps the entry.
    client.insert_rows("//users", [{"id": 1, "score": 99}], update=True)
    assert client.select_rows(
        "id, score FROM [//users] WHERE city = 'msk'") == [
        {"id": 1, "score": 99}]
    # Delete removes the index entry.
    client.delete_rows("//users", [(1,)])
    assert client.select_rows("city FROM [//by_city]") == []


def test_index_on_numeric_range(client):
    client.create_secondary_index("//users", "//by_score", ["score"])
    client.insert_rows("//users", [
        {"id": i, "city": "c", "score": i * 10} for i in range(8)])
    rows = client.select_rows(
        "id FROM [//users] WHERE score >= 30 AND score < 60")
    assert rows == [{"id": 3}, {"id": 4}, {"id": 5}]


def test_index_transactional_with_source(client):
    """An aborted transaction leaves no index entries behind."""
    client.create_secondary_index("//users", "//by_city", ["city"])
    tx = client.start_transaction()
    client.insert_rows("//users", [{"id": 5, "city": "kzn", "score": 5}],
                       tx=tx)
    client.abort_transaction(tx)
    assert client.select_rows("city FROM [//by_city]") == []
    assert client.lookup_rows("//users", [(5,)]) == [None]


def test_multiple_writes_same_key_one_tx(client):
    """Read-your-writes: two writes to one key in one transaction must not
    leave a stale index entry for the intermediate value."""
    client.create_secondary_index("//users", "//by_city", ["city"])
    tx = client.start_transaction()
    client.insert_rows("//users", [{"id": 1, "city": "aaa", "score": 1}],
                       tx=tx)
    client.insert_rows("//users", [{"id": 1, "city": "bbb", "score": 2}],
                       tx=tx)
    client.commit_transaction(tx)
    assert client.select_rows("city, id FROM [//by_city]") == [
        {"city": b"bbb", "id": 1}]
    rows = client.select_rows("id FROM [//users] WHERE city >= 'aaa'")
    assert rows == [{"id": 1}]


def test_drop_index(client):
    client.create_secondary_index("//users", "//by_city", ["city"])
    client.drop_secondary_index("//users", "//by_city")
    assert not client.exists("//by_city")
    # Writes no longer maintain it; queries fall back to scans.
    client.insert_rows("//users", [{"id": 1, "city": "spb", "score": 1}])
    assert client.select_rows(
        "id FROM [//users] WHERE city = 'spb'") == [{"id": 1}]


def test_create_validates(client):
    with pytest.raises(YtError):
        client.create_secondary_index("//users", "//idx", ["nope"])
    client.write_table("//static", [{"a": 1}])
    with pytest.raises(YtError):
        client.create_secondary_index("//static", "//idx", ["a"])
