"""Lease-based leader election for the metadata master.

Ref: Hydra's election + lease tracking (yt/yt/server/lib/election/,
yt/yt/server/lib/hydra/lease_tracker.h): peers vote, the leader holds a
lease it renews continuously, followers take over when the lease lapses.

Design delta for this build: there is no separate election cell — the
JOURNAL locations (data nodes holding the quorum WAL) double as the vote
and lease plane, because they already arbitrate write ownership through
epoch fencing.  Leadership means holding an unexpired lease on a STRICT
MAJORITY of journal locations:

  - acquisition piggybacks on epoch acquisition (journal_acquire grants
    the lease together with the epoch vote, so a freshly elected leader
    is lease-covered before it serves a single write);
  - the leader renews on every location each ttl/3; losing a majority of
    renewals for a full ttl means leadership is lost (step down);
  - candidates poll lease state and attempt takeover only when a
    majority of locations answer AND none reports an unexpired lease
    held by someone else — plus a per-candidate hold-down so two
    standbys don't duel at the same instant.

Safety does NOT rest on the lease schedule: even if two candidates race,
epoch fencing in the quorum WAL guarantees at most one of them can reach
append quorum — the loser fail-stops on its first write.  The lease only
provides liveness and disruption-freedom (a healthy leader is not fenced
by a flapping standby, because journal_acquire refuses grants while an
unexpired foreign lease stands).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("election")


class LeaderElector:
    def __init__(self, journal_name: str, channels,
                 writer_id: str, lease_ttl: float = 6.0,
                 poll_interval: float = 0.5,
                 hold_down: float = 0.0):
        """channels: a list of journal-node channels, or a CALLABLE
        returning the current list — membership can grow after recovery
        (QuorumWal.extend), and both renewal and the majority threshold
        must follow it or the lease cover shrinks to a stale subset."""
        self.journal_name = journal_name
        self._channels_src = channels
        self.writer_id = writer_id
        self.lease_ttl = lease_ttl
        self.poll_interval = poll_interval
        # Deterministic stagger (e.g. master index * 1.5s): the first
        # candidate usually wins before the second even tries.
        self.hold_down = hold_down
        self._stop = threading.Event()
        self._renew_thread: Optional[threading.Thread] = None

    def _channels(self) -> list:
        if callable(self._channels_src):
            return list(self._channels_src())
        return list(self._channels_src)

    def _majority(self, channels) -> int:
        return len(channels) // 2 + 1

    # -- candidate side --------------------------------------------------------

    def _lease_states(self, channels) -> list[dict]:
        states = []
        for channel in channels:
            try:
                body, _ = channel.call(
                    "data_node", "journal_lease",
                    {"journal": self.journal_name})
                states.append(body)
            except YtError:
                continue
        return states

    def wait_until_electable(self, timeout: Optional[float] = None) -> bool:
        """Block until a takeover attempt is warranted: a majority of
        journal locations answer and none holds an unexpired foreign
        lease.  Returns False on stop/timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        held_down_until = time.monotonic() + self.hold_down
        while not self._stop.is_set():
            if deadline is not None and time.monotonic() > deadline:
                return False
            channels = self._channels()
            states = self._lease_states(channels)
            foreign = [s for s in states
                       if float(s.get("remaining", 0)) > 0
                       and _text(s.get("writer")) != self.writer_id]
            if foreign:
                # A live leader exists; check again when its lease could
                # have lapsed.
                self._stop.wait(min(
                    max(float(s.get("remaining", 0)) for s in foreign),
                    self.lease_ttl))
                held_down_until = time.monotonic() + self.hold_down
                continue
            if len(states) < self._majority(channels):
                self._stop.wait(self.poll_interval)
                continue
            if time.monotonic() < held_down_until:
                self._stop.wait(self.poll_interval)
                continue
            return True
        return False

    # -- leader side -----------------------------------------------------------

    def start_renewing(self, epoch,
                       on_lost: Callable[[], None]) -> None:
        """Renew the lease on every journal location each ttl/3; if a
        strict majority has not confirmed a renewal for a full ttl,
        leadership is lost and `on_lost` fires (once).

        `epoch` may be a callable returning the CURRENT epoch: the WAL
        re-acquires a higher epoch when it recovers from an orphaned
        fence, and renewals carrying the stale number would be denied
        everywhere, self-terminating a healthy leader."""
        epoch_fn = epoch if callable(epoch) else (lambda: epoch)

        def loop():
            last_majority = time.monotonic()
            while not self._stop.is_set():
                channels = self._channels()
                acks = 0
                for channel in channels:
                    try:
                        body, _ = channel.call(
                            "data_node", "journal_lease_renew",
                            {"journal": self.journal_name,
                             "epoch": epoch_fn(),
                             "writer": self.writer_id,
                             "ttl": self.lease_ttl}, idempotent=False)
                        if body.get("granted"):
                            acks += 1
                    except YtError:
                        continue
                now = time.monotonic()
                if acks >= self._majority(channels):
                    last_majority = now
                elif now - last_majority > self.lease_ttl:
                    logger.warning(
                        "leader lease lost (no majority for %.1fs)",
                        now - last_majority)
                    on_lost()
                    return
                self._stop.wait(self.lease_ttl / 3.0)

        self._renew_thread = threading.Thread(target=loop, daemon=True,
                                              name="lease-renew")
        self._renew_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._renew_thread is not None:
            self._renew_thread.join(timeout=5)


def _text(value) -> str:
    if isinstance(value, bytes):
        return value.decode()
    return value or ""
