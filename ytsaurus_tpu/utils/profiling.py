"""Typed sensors on a tag tree + Prometheus-format export.

Ref shape: library/profiling (TProfiler: counters/gauges/summaries/
histograms registered under a tag tree, per-CPU sharded) and
library/profiling/solomon/exporter.h:25 (pull endpoint scraped by the
monitoring system, Prometheus-compatible rendering).

Redesign: one process-wide `ProfilerRegistry`; a `Profiler` is a (prefix,
tags) view onto it.  Sensors are lock-striped rather than per-CPU — host
Python threads, not fibers, are the concurrency unit here.  Rendering is
Prometheus text exposition (the de-facto pull format); the HTTP endpoint
lives on each daemon's monitoring server (`server/monitoring.py`).
"""

from __future__ import annotations

import bisect
import random
import threading
import time
from collections import deque
from typing import Optional

from ytsaurus_tpu.utils import sanitizers


def _escape_label_value(value) -> str:
    """Prometheus exposition escaping for label values: backslash,
    double quote, and newline must be escaped or the scrape line is
    grammatically invalid (the exposition-validator test enforces it)."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _format_tags(tags: dict[str, str]) -> str:
    if not tags:
        return ""
    inner = ",".join(
        f'{_sanitize(str(k))}="{_escape_label_value(v)}"'
        for k, v in sorted(tags.items()))
    return "{" + inner + "}"


def _sanitize(name: str) -> str:
    return name.strip("/").replace("/", "_").replace("-", "_").replace(".", "_")


class Counter:
    """Monotone counter."""

    kind = "counter"

    def __init__(self):
        # guards: _value
        self._lock = sanitizers.register_lock("profiling.Counter._lock")
        self._value = 0.0

    def increment(self, delta: float = 1.0) -> None:
        with self._lock:
            self._value += delta

    def get(self) -> float:
        return self._value

    def samples(self):
        yield "counter", "", self._value

    def history_sample(self):
        return self._value


class Gauge:
    """Last-set value."""

    kind = "gauge"

    def __init__(self):
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def get(self) -> float:
        return self._value

    def samples(self):
        yield "gauge", "", self._value

    def history_sample(self):
        return self._value


class Summary:
    """Count/sum/min/max/last of observed values, plus a BOUNDED
    quantile reservoir.

    The reservoir is Vitter's algorithm R: a fixed-size uniform sample
    of every observation so far, so a month-long daemon's sensor memory
    stays O(RESERVOIR_CAPACITY) no matter how many values it records
    (the ISSUE 6 satellite: an unbounded per-sensor value list would
    grow without bound at serving rates).  `quantile()` reads it for
    p50/p99-style estimates."""

    kind = "summary"
    RESERVOIR_CAPACITY = 512

    def __init__(self):
        # guards: count, sum, min, max, last, _reservoir
        self._lock = sanitizers.register_lock("profiling.Summary._lock")
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.last = 0.0
        self._reservoir: list[float] = []

    def record(self, value: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += value
            self.min = min(self.min, value)
            self.max = max(self.max, value)
            self.last = value
            if len(self._reservoir) < self.RESERVOIR_CAPACITY:
                self._reservoir.append(value)
            else:
                j = random.randrange(self.count)
                if j < self.RESERVOIR_CAPACITY:
                    self._reservoir[j] = value

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1) from the bounded reservoir."""
        with self._lock:
            if not self._reservoir:
                return 0.0
            ordered = sorted(self._reservoir)
        idx = min(int(q * len(ordered)), len(ordered) - 1)
        return ordered[idx]

    def samples(self):
        yield "summary", ".sum", self.sum
        yield "summary", ".count", self.count
        if self.count:
            yield "summary", ".min", self.min
            yield "summary", ".max", self.max

    def history_sample(self):
        return (self.count, self.sum)


class Histogram:
    """Fixed-bucket histogram (upper bounds; +Inf implicit)."""

    kind = "histogram"
    DEFAULT_BOUNDS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                      30.0, 60.0)

    def __init__(self, bounds=None):
        self.bounds = tuple(bounds or self.DEFAULT_BOUNDS)
        # guards: buckets, count, sum
        self._lock = sanitizers.register_lock(
            "profiling.Histogram._lock")
        self.buckets = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0

    def record(self, value: float) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.buckets[idx] += 1
            self.count += 1
            self.sum += value

    def samples(self):
        cumulative = 0
        for bound, n in zip(self.bounds, self.buckets):
            cumulative += n
            yield "histogram", f'.bucket{{le="{bound}"}}', cumulative
        yield "histogram", '.bucket{le="+Inf"}', self.count
        yield "histogram", ".sum", self.sum
        yield "histogram", ".count", self.count

    def history_sample(self):
        # Raw per-bucket counts (NOT cumulative): window deltas then
        # subtract elementwise and quantile math cumsums the result.
        return (self.count, self.sum, tuple(self.buckets))


class Timer:
    """Context manager recording elapsed seconds into a Summary/Histogram."""

    def __init__(self, sensor):
        self._sensor = sensor

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._sensor.record(time.perf_counter() - self._t0)
        return False


class ProfilerRegistry:
    """All sensors of one process, keyed by (name, frozen tags)."""

    def __init__(self):
        # guards: _sensors
        self._lock = sanitizers.register_lock(
            "profiling.ProfilerRegistry._lock")
        self._sensors: dict[tuple, object] = {}

    def _get(self, name: str, tags: dict, factory):
        key = (name, tuple(sorted(tags.items())))
        with self._lock:
            sensor = self._sensors.get(key)
            if sensor is None:
                sensor = self._sensors[key] = factory()
            return sensor

    def render_prometheus(self) -> str:
        """Text exposition format, stable ordering."""
        lines = []
        with self._lock:
            items = sorted(self._sensors.items(),
                           key=lambda kv: (kv[0][0], kv[0][1]))
        for (name, tags), sensor in items:
            metric = _sanitize(name)
            tag_str = _format_tags(dict(tags))
            for _kind, suffix, value in sensor.samples():
                if suffix.startswith(".bucket"):
                    # merge histogram le-tag with sensor tags
                    le = suffix[len(".bucket"):]
                    base = tag_str[:-1] + "," + le[1:] if tag_str \
                        else le
                    lines.append(f"{metric}_bucket{base} {value}")
                else:
                    lines.append(
                        f"{metric}{suffix.replace('.', '_')}{tag_str} "
                        f"{value}")
        return "\n".join(lines) + ("\n" if lines else "")

    def collect(self) -> dict:
        """Live snapshot as a plain dict (Orchid's data source)."""
        out = {}
        with self._lock:
            items = list(self._sensors.items())
        for (name, tags), sensor in items:
            entry = {suffix or "value": value
                     for _k, suffix, value in sensor.samples()
                     if not suffix.startswith(".bucket")}
            key = name + _format_tags(dict(tags))
            out[key] = entry if len(entry) > 1 else next(iter(entry.values()))
        return out


_global_registry = ProfilerRegistry()


def get_registry() -> ProfilerRegistry:
    return _global_registry


# ---------------------------------------------------------------------------
# Metrics history: bounded in-process time-series rings (ISSUE 6 tentpole).
#
# Ref shape: Solomon-style metrics history — the reference's monitoring
# system keeps per-sensor time series the dashboards and alerts read;
# here each process keeps its own bounded rings (a sampler thread
# snapshots every registered sensor at TelemetryConfig.sample_period)
# served via /metrics/history and orchid /telemetry/history, and the
# primary's /cluster roll-up scrapes every daemon's rings for the fleet
# view.  Two tiers bound memory while keeping both resolutions: fine
# (sample_period x fine_capacity, default 10s x 360 = 1h) and coarse
# (every coarse_every-th sample, default 5min x 288 = 24h).
# ---------------------------------------------------------------------------


class _SeriesRing:
    """One sensor's bounded history: (timestamp, history_sample) points
    in two fixed-size deques.  Counter/gauge points carry a float;
    summaries (count, sum); histograms (count, sum, raw buckets)."""

    __slots__ = ("kind", "bounds", "fine", "coarse")

    def __init__(self, kind: str, bounds, fine_capacity: int,
                 coarse_capacity: int):
        self.kind = kind
        self.bounds = bounds            # histogram upper bounds, else None
        self.fine: deque = deque(maxlen=fine_capacity)
        self.coarse: deque = deque(maxlen=coarse_capacity)

    def points(self, tier: str) -> list:
        return list(self.coarse if tier == "coarse" else self.fine)

    def at_or_before(self, ts: float):
        """Newest point with timestamp <= ts, preferring fine resolution
        and falling back to the coarse tier for older horizons."""
        for tier in (self.fine, self.coarse):
            best = None
            for point in tier:
                if point[0] <= ts:
                    best = point
                else:
                    break
            if best is not None:
                return best
        # Nothing old enough: the oldest point we still hold (best
        # effort — a window larger than retention reads what's left).
        if self.coarse:
            return self.coarse[0]
        return self.fine[0] if self.fine else None

    def latest(self):
        if self.fine:
            return self.fine[-1]
        return self.coarse[-1] if self.coarse else None


class MetricsHistory:
    """Bounded history of every sensor in one registry.

    `sample_once(now)` snapshots all sensors (tests drive it with a
    synthetic timeline; daemons run a TelemetrySampler thread).  Memory
    is bounded by construction: one _SeriesRing of fixed-size deques per
    live sensor, no per-event storage."""

    def __init__(self, registry: Optional[ProfilerRegistry] = None,
                 fine_capacity: int = 360, coarse_every: int = 30,
                 coarse_capacity: int = 288,
                 sample_period: float = 10.0):
        self.registry = registry or _global_registry
        self.fine_capacity = fine_capacity
        self.coarse_every = max(coarse_every, 1)
        self.coarse_capacity = coarse_capacity
        self.sample_period = sample_period
        # guards: _series, samples_taken
        self._lock = sanitizers.register_lock(
            "profiling.MetricsHistory._lock")
        self._series: dict[tuple, _SeriesRing] = {}
        self.samples_taken = 0

    @classmethod
    def from_config(cls, cfg,
                    registry: Optional[ProfilerRegistry] = None
                    ) -> "MetricsHistory":
        return cls(registry=registry, fine_capacity=cfg.fine_capacity,
                   coarse_every=cfg.coarse_every,
                   coarse_capacity=cfg.coarse_capacity,
                   sample_period=cfg.sample_period)

    def sample_once(self, now: Optional[float] = None) -> float:
        now = time.time() if now is None else now
        with self.registry._lock:
            items = list(self.registry._sensors.items())
        with self._lock:
            self.samples_taken += 1
            fold = self.samples_taken % self.coarse_every == 0
            for key, sensor in items:
                ring = self._series.get(key)
                if ring is None:
                    ring = self._series[key] = _SeriesRing(
                        getattr(sensor, "kind", "gauge"),
                        getattr(sensor, "bounds", None),
                        self.fine_capacity, self.coarse_capacity)
                point = (now, sensor.history_sample())
                ring.fine.append(point)
                if fold:
                    ring.coarse.append(point)
        return now

    # -- queries ---------------------------------------------------------------

    def _matching(self, name: Optional[str], tags: Optional[dict]):
        with self._lock:
            items = list(self._series.items())
        for (sname, stags), ring in items:
            if name is not None and sname != name:
                continue
            if tags:
                stag_dict = dict(stags)
                if any(stag_dict.get(k) != v for k, v in tags.items()):
                    continue
            yield (sname, stags), ring

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted({name for name, _tags in self._series})

    def query(self, name: Optional[str] = None,
              tags: Optional[dict] = None,
              since: Optional[float] = None,
              tier: str = "fine") -> list[dict]:
        """Matching series as JSON-shaped dicts (the /metrics/history
        payload).  `tags` is a subset filter; `since` drops points at or
        before that timestamp; `tier` picks fine or coarse."""
        out = []
        for (sname, stags), ring in self._matching(name, tags):
            points = ring.points(tier)
            if since is not None:
                points = [p for p in points if p[0] > since]
            out.append({
                "name": sname, "tags": dict(stags), "kind": ring.kind,
                "tier": tier,
                "points": [[ts, value] for ts, value in points],
            })
        out.sort(key=lambda s: (s["name"], sorted(s["tags"].items())))
        return out

    def window_delta(self, name: str, tags: Optional[dict] = None,
                     window: float = 300.0,
                     now: Optional[float] = None):
        """Cumulative-series change over the trailing window, summed
        across matching series: counters return a float; summaries
        (d_count, d_sum); histograms (d_count, d_sum, [d_buckets],
        bounds).  None when no matching series holds two points yet.
        Gauges return the latest value (deltas are meaningless)."""
        total = None
        for _key, ring in self._matching(name, tags):
            latest = ring.latest()
            if latest is None:
                continue
            t_latest = latest[0]
            horizon = (now if now is not None else t_latest) - window
            base = ring.at_or_before(horizon)
            if base is None or base[0] >= t_latest:
                continue
            if ring.kind == "gauge":
                delta = latest[1]
            elif ring.kind == "counter":
                delta = latest[1] - base[1]
            elif ring.kind == "summary":
                delta = (latest[1][0] - base[1][0],
                         latest[1][1] - base[1][1])
            else:                                   # histogram
                delta = (latest[1][0] - base[1][0],
                         latest[1][1] - base[1][1],
                         [a - b for a, b in zip(latest[1][2],
                                                base[1][2])],
                         ring.bounds)
            total = delta if total is None else _merge_delta(total, delta)
        return total

    def dump(self) -> dict:
        """Orchid /telemetry/history producer: every series keyed the
        same way registry.collect keys sensors."""
        series = {}
        for (sname, stags), ring in self._matching(None, None):
            series[sname + _format_tags(dict(stags))] = {
                "kind": ring.kind,
                "fine": [[ts, value] for ts, value in ring.points("fine")],
                "coarse": [[ts, value]
                           for ts, value in ring.points("coarse")],
            }
        return {"samples_taken": self.samples_taken,
                "sample_period": self.sample_period,
                "series": series}


def _merge_delta(a, b):
    if isinstance(a, tuple) and isinstance(b, tuple):
        if len(a) >= 3:            # histogram: merge buckets elementwise
            return (a[0] + b[0], a[1] + b[1],
                    [x + y for x, y in zip(a[2], b[2])], a[3])
        return tuple(x + y for x, y in zip(a, b))
    return a + b


class TelemetrySampler:
    """The sampler thread: snapshots the registry into a MetricsHistory
    at a fixed cadence, then runs the follow-up hooks (SLO evaluation)."""

    def __init__(self, history: MetricsHistory,
                 period: Optional[float] = None, hooks=()):
        self.history = history
        self.period = history.sample_period if period is None else period
        self.hooks = list(hooks)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TelemetrySampler":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="telemetry-sampler")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            self.tick()

    def tick(self) -> None:
        now = self.history.sample_once()
        for hook in self.hooks:
            try:
                hook(now)
            except Exception:   # noqa: BLE001 — one bad SLO config must
                # not kill the sampling cadence for every other series.
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


_global_history: Optional[MetricsHistory] = None
_global_sampler: Optional[TelemetrySampler] = None
# guards: _global_history, _global_sampler
_history_lock = sanitizers.register_lock("profiling._history_lock",
                                         hot=False)


def get_history() -> MetricsHistory:
    """The process-wide history rings (lazily built from
    config.telemetry_config)."""
    global _global_history
    if _global_history is None:
        with _history_lock:
            if _global_history is None:
                from ytsaurus_tpu.config import telemetry_config
                _global_history = MetricsHistory.from_config(
                    telemetry_config())
    return _global_history


def configure_telemetry(cfg) -> None:
    """Rebuild the global history to a new config's ring shape (called
    by config.set_telemetry_config; None restores lazy defaults).  A
    RUNNING sampler is restarted against the new rings + SLO tracker —
    otherwise a live daemon's reconfigure would leave the old thread
    sampling orphaned rings forever (set_telemetry_config rebinds the
    SLO tracker BEFORE calling here, so the restart hooks the new one)."""
    global _global_history, _global_sampler
    with _history_lock:
        _global_history = None if cfg is None \
            else MetricsHistory.from_config(cfg)
        sampler = _global_sampler
        _global_sampler = None
    if sampler is not None:
        sampler.stop()
        start_telemetry(cfg)


def start_telemetry(config=None) -> Optional[TelemetrySampler]:
    """Start (once) the process-wide sampler + SLO evaluation — the
    daemon entry point's one-call telemetry bring-up.  Returns the
    sampler, or None when sampling is disabled."""
    global _global_sampler
    if config is None:
        from ytsaurus_tpu.config import telemetry_config
        config = telemetry_config()
    if not config.enabled or config.sample_period <= 0:
        return None
    with _history_lock:
        if _global_sampler is not None:
            return _global_sampler
    from ytsaurus_tpu.utils.slo import get_slo_tracker
    tracker = get_slo_tracker()
    sampler = TelemetrySampler(get_history(), config.sample_period,
                               hooks=[tracker.evaluate])
    with _history_lock:
        if _global_sampler is None:
            _global_sampler = sampler.start()
    return _global_sampler


class Profiler:
    """A (prefix, tags) view: `Profiler('/query', {'pool': 'prod'})`.

    Ref TProfiler semantics: `.with_tags()` refines, sensor getters
    create-or-fetch.
    """

    def __init__(self, prefix: str = "", tags: Optional[dict] = None,
                 registry: Optional[ProfilerRegistry] = None):
        self.prefix = prefix
        self.tags = dict(tags or {})
        self.registry = registry or _global_registry

    def with_prefix(self, prefix: str) -> "Profiler":
        return Profiler(self.prefix + prefix, self.tags, self.registry)

    def with_tags(self, **tags) -> "Profiler":
        return Profiler(self.prefix, {**self.tags, **tags}, self.registry)

    def _name(self, name: str) -> str:
        return f"{self.prefix}/{name}" if self.prefix else name

    def counter(self, name: str) -> Counter:
        return self.registry._get(self._name(name), self.tags, Counter)

    def gauge(self, name: str) -> Gauge:
        return self.registry._get(self._name(name), self.tags, Gauge)

    def summary(self, name: str) -> Summary:
        return self.registry._get(self._name(name), self.tags, Summary)

    def histogram(self, name: str, bounds=None) -> Histogram:
        return self.registry._get(self._name(name), self.tags,
                                  lambda: Histogram(bounds))

    def timer(self, name: str) -> Timer:
        return Timer(self.summary(name))


class PoolSensorCache:
    """Memoized per-pool counter sets: `counters(pool)` returns
    {name: Counter} tagged `pool=` (the untagged parent sensors when
    pool is None/empty).  The one shared shape behind the evaluator's
    compile-cache counters, the tablet's lookup counters, and the
    accountant's usage mirrors — hot paths pay a dict probe, not a
    registry lock, after the first use of a pool.

    `tools/check_sensor_catalog.py` resolves these constructors
    statically: keep `prefix` (and `names`, where the set is fixed) as
    literals at the construction site."""

    __slots__ = ("_profiler", "names", "_cache")

    def __init__(self, prefix: str, names,
                 registry: Optional[ProfilerRegistry] = None):
        self._profiler = Profiler(prefix, registry=registry)
        self.names = tuple(names)
        self._cache: dict = {}

    def counters(self, pool) -> dict:
        entry = self._cache.get(pool)
        if entry is None:
            prof = self._profiler.with_tags(pool=pool) if pool \
                else self._profiler
            entry = self._cache[pool] = {name: prof.counter(name)
                                         for name in self.names}
        return entry
